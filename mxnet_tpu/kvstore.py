"""Key-value store for parameter synchronization.

Parity surface: ``python/mxnet/kvstore.py`` (KVStore :97 — init/push/pull/
row_sparse_pull/set_optimizer/compression) backed in the reference by
src/kvstore/ (CommCPU/CommDevice reduce trees, RCCL, ps-lite dist servers).

TPU-native design (SURVEY.md §2.3 / §7): the device-reduce layer collapses
into XLA collectives —

* ``local`` / ``device``: in-process aggregation. Multiple per-device values
  for one key are summed with a single jitted reduce (the CommDevice analog;
  XLA emits the optimal reduction on one chip, and cross-device eager reduce
  rides ICI when multiple chips exist).
* ``tpu_sync`` (the reference's ``dist_sync_device`` → BASELINE north star):
  same push/pull surface; the intended fast path is *inside* the jitted SPMD
  train step (Module/Trainer fuse grad-psum over the mesh into the step, so
  push/pull become no-ops there). Standalone push/pull still work and
  all-reduce over data-parallel replicas.
* ``dist_sync``/``dist_async``: multi-host over jax.distributed (DCN);
  single-process fallback behaves like local (matching the reference's
  1-worker dist behavior).

``update_on_kvstore`` semantics are preserved: when an optimizer is set, push
aggregates gradients and applies the update; pull returns fresh weights.
"""
from __future__ import annotations

import pickle

import numpy as _np

from .base import MXNetError
from .ndarray import ndarray as _nd
from .ndarray.ndarray import NDArray
from .ndarray import sparse as _sp
from . import optimizer as _opt

__all__ = ["KVStore", "create"]


class KVStore:
    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._residuals = {}
        self._last_wire_bytes = None   # observability: payload of last push
        # dist_*: join the launcher's process group (reference: ps-lite van
        # connects on kvstore_dist construction); cross-process reduction
        # then happens in push. Single-process dist degrades to local.
        self._dist = False
        self._async_server = None
        self._async_client = None
        if kv_type.startswith("dist"):
            from .parallel import dist as _dist
            self._dist = _dist.init() and _dist.num_workers() > 1
        if self._dist and kv_type == "dist_async":
            self._start_async()

    def _start_async(self):
        """dist_async topology: rank 0 hosts the apply-on-push server
        thread (parallel/async_server.py), every rank connects a client.
        One startup broadcast shares the port; after that there are NO
        inter-worker barriers — each rank pushes/pulls at its own pace
        (reference kvstore_dist_server.h:348-358 ApplyUpdates async arm)."""
        import os
        import numpy as _np2
        from .parallel import dist as _dist
        from .parallel import async_server as _async
        def coordinator_host():
            """Host of the job coordinator: launcher env, else the address
            an externally-initialized jax.distributed actually dialed
            (rank 0's machine — the same machine hosting the server
            thread)."""
            addr = _dist.env_spec()[0]
            if addr is None:
                try:
                    from jax._src import distributed as _jd
                    addr = _jd.global_state.coordinator_address
                except Exception:
                    addr = None
            return _async._host_of(addr) if addr else None

        if _dist.rank() == 0:
            # materialize the jax backend on the MAIN thread first: the
            # server thread applies pushes through jax, and letting it
            # trigger the (distributed, topology-exchanging) backend init
            # races the other ranks' init ("global_topology already
            # exists" gRPC failures)
            import jax
            jax.devices()
            # with a job secret the server binds the coordinator interface
            # (reachable by remote workers, frames authenticated); without
            # one it stays loopback-only — see async_server.py trust model
            bind = None
            if os.environ.get("MXNET_KVSTORE_SECRET") and \
                    not os.environ.get("MXNET_KVSTORE_BIND"):
                bind = coordinator_host()
            self._async_server = _async.Server(bind=bind)
            port = self._async_server.port
        else:
            port = 0
        port = int(_np2.asarray(
            _dist.broadcast(_np2.array([port], _np2.int32)))[0])
        host = os.environ.get("MXNET_ASYNC_SERVER_HOST") \
            or coordinator_host() or "127.0.0.1"
        self._async_client = _async.Client(host, port)

    # ------------------------------------------------------------- metadata
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        from .parallel import dist as _dist
        return _dist.rank()

    @property
    def num_workers(self):
        from .parallel import dist as _dist
        return _dist.num_workers()

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Number of workers whose heartbeat went stale (reference
        kvstore.h:353, ps-lite scheduler heartbeats). ``node_id`` selects
        the ps-lite node group in the reference; here only workers exist,
        so it is accepted and ignored. Liveness comes from the per-rank
        heartbeat files the launcher provisions (parallel/fault.py); a
        PJRT coordination-service failure additionally surfaces as a
        failed collective."""
        from .parallel import fault as _fault
        return len(_fault.dead_nodes(self.num_workers, timeout=timeout))

    # ----------------------------------------------------------------- init
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            v0 = v[0] if isinstance(v, list) else v
            if self._async_client is not None:
                dense = v0.todense() if isinstance(
                    v0, _sp.BaseSparseNDArray) else v0
                self._async_client.call("init", k, dense.asnumpy())
                self._store[k] = v0.copy()  # shape/dtype template for pull
                continue
            if self._dist:
                # reference: init lands on the server once; here rank 0's
                # value is broadcast so every replica starts identical
                from .parallel import dist as _dist
                if isinstance(v0, _sp.BaseSparseNDArray):
                    dense = _dist.broadcast(v0.todense()._data)
                    self._store[k] = _sp.cast_storage(
                        NDArray(dense, ctx=v0.context), v0.stype)
                else:
                    self._store[k] = NDArray(_dist.broadcast(v0._data),
                                             ctx=v0.context)
            else:
                self._store[k] = v0.copy()

    # ----------------------------------------------------------------- push
    def push(self, key, value, priority=0):
        keys, values = _key_value(key, value)
        for k, vs in zip(keys, values):
            if not isinstance(vs, list):
                vs = [vs]
            agg = self._reduce(vs)
            if self._async_client is not None:
                self._push_async(k, agg)
                continue
            if self._compression_params and self._dist and \
                    not isinstance(agg, _sp.BaseSparseNDArray):
                # wire-level path: 2-bit codes packed 4-per-uint8 cross the
                # network (~16x smaller than f32), summed after unpacking
                # (reference gradient_compression.h:38-132 ships quantized
                # data the same way); residual error-feedback stays local
                agg = self._dist_reduce_2bit(k, agg)
            else:
                if self._compression_params:
                    # in-process: same quantize->dequantize roundtrip, so
                    # convergence behavior matches the dist path
                    agg = self._compress(k, agg)
                if self._dist:
                    agg = self._dist_reduce(agg)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("key %r not initialized" % k)
                self._updater(k, agg, self._store[k])
            else:
                # no updater: the merged push REPLACES the stored value
                # (reference kvstore_local.h PushImpl `local = merged`;
                # python/mxnet/kvstore.py push docstring examples)
                self._store[k] = agg

    def _push_async(self, k, agg):
        """dist_async: ship this worker's gradient to the server, which
        applies it immediately — no cross-worker reduce, no barrier."""
        if isinstance(agg, _sp.BaseSparseNDArray):
            agg = agg.todense()
        if self._compression_params:
            packed, shape, thr = self._quantize_wire(k, agg)
            self._last_wire_bytes = packed.nbytes
            self._async_client.call("pushq", k, packed, shape, thr)
        else:
            g = agg.asnumpy()
            self._last_wire_bytes = g.nbytes
            self._async_client.call("push", k, g)

    def _quantize_wire(self, key, grad):
        """Worker-side 2-bit quantization producing the PACKED wire form
        (4 codes per uint8). Residual error-feedback is kept locally."""
        import jax.numpy as jnp
        thr = self._compression_params["threshold"]
        g = grad._data if isinstance(grad, NDArray) else jnp.asarray(grad)
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros_like(g)
        packed, new_res = _pack_2bit(g, res, thr)
        self._residuals[key] = new_res
        return _np.asarray(packed), tuple(g.shape), thr

    def _dist_reduce_2bit(self, key, agg):
        """dist_sync with compression: allgather the packed codes (the
        only cross-network payload), unpack+dequantize+sum locally."""
        from .parallel import dist as _dist
        packed, shape, thr = self._quantize_wire(key, agg)
        self._last_wire_bytes = packed.nbytes
        gathered = _np.asarray(_dist.allgather(packed))   # (W, nbytes)
        total = None
        for row in gathered:
            d = _dequantize_2bit(row, shape, thr)
            total = d if total is None else total + d
        import jax.numpy as jnp
        return NDArray(jnp.asarray(total), ctx=agg.context)

    def _dist_reduce(self, agg):
        """Cross-process sum (the reference's worker->server aggregation,
        as a symmetric all-reduce). Every rank must push the same keys in
        the same order — dist_sync semantics."""
        from .parallel import dist as _dist
        if isinstance(agg, _sp.BaseSparseNDArray):
            stype = agg.stype
            dense = _dist.allreduce_sum(agg.todense()._data)
            return _sp.cast_storage(NDArray(dense, ctx=agg.context), stype)
        return NDArray(_dist.allreduce_sum(agg._data), ctx=agg.context)

    def _reduce(self, vs):
        """Sum a list of per-device values (CommDevice::Reduce analog —
        one fused XLA add chain instead of tree scheduling)."""
        if len(vs) == 1:
            v0 = vs[0]
            return v0.copy() if not isinstance(v0, _sp.BaseSparseNDArray) else v0
        if any(isinstance(v, _sp.RowSparseNDArray) for v in vs):
            out = vs[0]
            for v in vs[1:]:
                out = _sp.add(out, v)
            return out if isinstance(out, _sp.RowSparseNDArray) \
                else _sp.cast_storage(out, "row_sparse")
        acc = vs[0]._data
        for v in vs[1:]:
            acc = acc + v._data.astype(acc.dtype)
        return NDArray(acc, ctx=vs[0].context)

    # ----------------------------------------------------------------- pull
    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """reference kvstore.pull: row_sparse values are SKIPPED under the
        default ignore_sparse=True (use row_sparse_pull for them);
        ignore_sparse=False copies them (densifying into dense outs)."""
        keys, outs = _key_value(key, out)
        for k, os_ in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            if self._async_client is not None:
                # async: fetch whatever the server's weights are RIGHT NOW
                import jax.numpy as jnp
                cur = self._async_client.call("pull", k)
                tmpl = self._store[k]
                src = NDArray(jnp.asarray(cur), ctx=tmpl.context)
                if isinstance(tmpl, _sp.BaseSparseNDArray):
                    src = _sp.cast_storage(src, tmpl.stype)
            else:
                src = self._store[k]
            if isinstance(src, _sp.RowSparseNDArray) and ignore_sparse:
                continue
            if not isinstance(os_, list):
                os_ = [os_]
            for o in os_:
                if isinstance(src, _sp.BaseSparseNDArray):
                    if isinstance(o, _sp.RowSparseNDArray) and \
                            isinstance(src, _sp.RowSparseNDArray):
                        src.copyto(o)
                    else:
                        src.todense().copyto(o)
                else:
                    src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only requested rows (reference row_sparse_pull :314)."""
        keys, outs = _key_value(key, out)
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        rids = row_ids if isinstance(row_ids, list) else [row_ids]
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            if not isinstance(os_, list):
                os_ = [os_]
            if len(rids) == 1:
                rids = rids * len(os_)
            for o, rid in zip(os_, rids):
                if isinstance(src, _sp.RowSparseNDArray):
                    sub = src.retain(rid)
                else:
                    sub = _sp.retain(
                        _sp.cast_storage(src, "row_sparse"), rid)
                if isinstance(o, _sp.RowSparseNDArray):
                    sub.copyto(o)
                else:
                    sub.todense().copyto(o)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    broadcast = pull

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        if self._async_client is not None:
            # the update lives on the server (reference: kvstore.py
            # set_optimizer pickles the optimizer to the dist servers);
            # workers keep NO local updater — push applies remotely
            self._async_client.call("set_optimizer", pickle.dumps(optimizer))
            self._updater = None
            return
        self._updater = _opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with error-feedback residual
        (reference src/kvstore/gradient_compression.h:38-132). On TPU this is
        a DCN bandwidth optimization; in-process it faithfully reproduces the
        quantize→dequantize roundtrip so convergence behavior matches."""
        if compression_params.get("type") not in ("2bit",):
            raise MXNetError("unsupported compression type %r"
                             % compression_params.get("type"))
        self._compression_params = {
            "type": "2bit",
            "threshold": float(compression_params.get("threshold", 0.5))}

    def _compress(self, key, grad):
        import jax.numpy as jnp
        thr = self._compression_params["threshold"]
        g = grad._data if isinstance(grad, NDArray) else grad
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros_like(g)
        acc = g + res
        q = jnp.where(acc >= thr, thr,
                      jnp.where(acc <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residuals[key] = acc - q
        return NDArray(q, ctx=grad.context if isinstance(grad, NDArray) else None)

    # ------------------------------------------------------------- persist
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        from .parallel import dist as _dist
        _dist.barrier()

    def _send_command_to_servers(self, head, body):
        """Control message to the server group (reference
        include/mxnet/kvstore.h:49 — kSetOptimizer/profiler commands).
        Real for dist_async (delivered to the rank-0 server thread);
        refused loudly elsewhere — the other modes HAVE no server, and
        silently dropping a control message would fake success."""
        if self._async_client is not None:
            self._async_client.call("command", head, body)
            return
        raise MXNetError(
            "kvstore type %r has no parameter server to command "
            "(server-side control messages exist only for dist_async; "
            "sync modes run their updates inside the compiled step)"
            % self._type)


def _pack_2bit(g, res, thr):
    """Quantize g+res to {-thr, 0, +thr} and pack the 2-bit codes four per
    uint8 (code 1 = +thr, 2 = -thr, 0 = zero). Returns (packed uint8
    array, new residual). Pure jnp, so the whole thing is one fused XLA
    program on the accelerator before the bytes ever hit the host/wire
    (reference gradient_compression.cc packs on-device the same way)."""
    import jax.numpy as jnp
    acc = g + res
    plus = acc >= thr
    minus = acc <= -thr
    q = jnp.where(plus, thr, jnp.where(minus, -thr, 0.0)).astype(g.dtype)
    codes = (plus.astype(jnp.uint8) + 2 * minus.astype(jnp.uint8)).ravel()
    pad = (-codes.size) % 4
    if pad:
        codes = jnp.concatenate([codes, jnp.zeros((pad,), jnp.uint8)])
    c = codes.reshape(-1, 4)
    packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4) | (c[:, 3] << 6))
    return packed.astype(jnp.uint8), acc - q


def _dequantize_2bit(packed, shape, thr):
    """Unpack uint8-packed 2-bit codes back to a float32 array of
    ``shape`` (host-side numpy: runs on whichever end of the wire)."""
    packed = _np.asarray(packed, dtype=_np.uint8)
    n = int(_np.prod(shape)) if shape else 1
    codes = _np.empty((packed.size, 4), _np.uint8)
    codes[:, 0] = packed & 3
    codes[:, 1] = (packed >> 2) & 3
    codes[:, 2] = (packed >> 4) & 3
    codes[:, 3] = (packed >> 6) & 3
    lut = _np.array([0.0, thr, -thr, 0.0], _np.float32)
    return lut[codes.ravel()[:n]].reshape(shape)


def _key_value(key, value):
    if isinstance(key, (str, int)):
        return [key], [value]
    return list(key), list(value)


_VALID = {"local", "device", "local_allreduce", "local_device",
          "tpu_sync", "nccl", "dist_sync", "dist_async", "dist_sync_device",
          "dist_device_sync"}


def create(name="local"):
    if not isinstance(name, str) or name not in _VALID:
        raise ValueError("unknown kvstore type %r (valid: %s)"
                         % (name, sorted(_VALID)))
    if name.startswith("dist"):
        # multi-host: jax.distributed must have been initialized by the
        # launcher (tools/launch analog); single-process degenerates to local
        return KVStore(name)
    return KVStore(name)
