"""Symbolic graph construction.

Parity surface: ``python/mxnet/symbol/symbol.py`` (reference, 2,970 LoC) whose
C++ core is nnvm Symbol/Graph. TPU-native design: Symbol is a lightweight
Python DAG over the same op registry the eager path uses; *all* graph
optimization (memory planning, fusion, inplace, bulking — the reference's
src/executor/ passes) is delegated to XLA when the graph is bound
(executor.py traces the DAG into one jitted function). Shape/type inference
runs ``jax.eval_shape`` over the traced graph, with per-op parameter-shape
hooks to fill in unknown parameter shapes from data shapes (the reference's
FInferShape backward-inference, e.g. fully_connected.cc weight shape).
"""
from __future__ import annotations

import json
import logging

import numpy as _np

from ..base import MXNetError
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

def _auto_name(prefix, name=None):
    """Auto-name through the active NameManager (mx.name.Prefix etc.)."""
    from ..name import current as _name_current
    return _name_current().get(name, prefix)


class Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "inputs", "params", "attrs")

    def __init__(self, op, name, inputs, params, attrs=None):
        self.op = op                # Operator or None (variable)
        self.name = name
        self.inputs = inputs        # list[(Node, int)]
        self.params = params or {}  # op hyper-parameters
        self.attrs = attrs or {}    # user attrs (__ctx_group__, lr_mult, ...)

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        """Graph-visible output count (hidden aux-update outputs excluded)."""
        if self.op is None:
            return 1
        return self.op.resolve_num_visible_outputs(self.params)


class Symbol:
    """An output list over a DAG of Nodes."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)  # list[(Node, int)]

    # ------------------------------------------------------------- topology
    def _topo(self):
        """All nodes in topological order (inputs before consumers)."""
        seen = set()
        order = []
        stack = [(n, False) for n, _ in reversed(self._entries)]
        while stack:
            node, expanded = stack.pop()
            if expanded:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for (inp, _) in reversed(node.inputs):
                if id(inp) not in seen:
                    stack.append((inp, False))
        return order

    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return "group"

    def list_arguments(self):
        """Variable names in topo order, excluding auxiliary states."""
        aux = set(self.list_auxiliary_states())
        return [n.name for n in self._topo()
                if n.is_variable and n.name not in aux]

    def list_auxiliary_states(self):
        """Variables wired into ops' aux input slots (e.g. BatchNorm moving
        stats; reference aux_states concept)."""
        aux = []
        seen = set()
        for n in self._topo():
            if n.is_variable:
                continue
            aux_in = getattr(n.op, "aux_inputs", ()) or ()
            for i in aux_in:
                if i < len(n.inputs):
                    v = n.inputs[i][0]
                    if v.is_variable and v.name not in seen:
                        seen.add(v.name)
                        aux.append(v.name)
        return aux

    def list_outputs(self):
        out = []
        for node, idx in self._entries:
            if node.num_outputs() > 1:
                out.append("%s_output%d" % (node.name, idx))
            else:
                out.append("%s_output" % node.name)
        return out

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable]

    @property
    def num_outputs(self):
        return len(self._entries)

    # ------------------------------------------------------------ selection
    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._entries[index])
        return Symbol([self._entries[index]])

    def __iter__(self):
        for i in range(len(self._entries)):
            yield self[i]

    def __len__(self):
        return len(self._entries)

    def get_internals(self):
        """Symbol exposing every node's outputs (reference get_internals)."""
        entries = []
        for n in self._topo():
            for i in range(n.num_outputs()):
                entries.append((n, i))
        return Symbol(entries)

    def get_children(self):
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ------------------------------------------------------------ subgraph
    def get_backend_symbol(self, backend):
        """Partition this symbol with a registered subgraph backend's
        properties (reference Symbol.get_backend_symbol,
        src/operator/subgraph/)."""
        from ..subgraph import partition
        return partition(self, backend)

    # ----------------------------------------------------------- attributes
    def attr(self, key):
        return self._entries[0][0].attrs.get(key)

    def _set_attr(self, **kwargs):
        self._entries[0][0].attrs.update(kwargs)

    def attr_dict(self):
        out = {}
        for n in self._topo():
            if n.attrs:
                d = {k: v for k, v in n.attrs.items() if k != "__flow__"}
                if d:
                    out[n.name] = d
        return out

    # ------------------------------------------------------------ operators
    def __add__(self, other):
        return _sym_binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _sym_binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _sym_binary_r("broadcast_sub", "_rminus_scalar", self, other)

    def __mul__(self, other):
        return _sym_binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _sym_binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _sym_binary_r("broadcast_div", "_rdiv_scalar", self, other)

    def __pow__(self, other):
        return _sym_binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return invoke_sym("negative", [self], {})

    def __copy__(self):
        return Symbol(self._entries)

    def __eq__(self, other):
        if isinstance(other, Symbol):
            return _sym_binary("broadcast_equal", "_equal_scalar", self, other)
        if other is None:
            return False
        return _sym_binary("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        if other is None:
            return True
        return _sym_binary("broadcast_not_equal", "_not_equal_scalar",
                           self, other)

    def __gt__(self, other):
        return _sym_binary("broadcast_greater", "_greater_scalar",
                           self, other)

    def __ge__(self, other):
        return _sym_binary("broadcast_greater_equal",
                           "_greater_equal_scalar", self, other)

    def __lt__(self, other):
        return _sym_binary("broadcast_lesser", "_lesser_scalar",
                           self, other)

    def __le__(self, other):
        return _sym_binary("broadcast_lesser_equal",
                           "_lesser_equal_scalar", self, other)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        return "<Symbol %s>" % self.name

    # --------------------------------------------------------------- infer
    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes); None entries where
        inference failed (reference symbol.py infer_shape)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        known = dict(kwargs)
        if args:
            for name, shp in zip(self.list_arguments(), args):
                if shp is not None:
                    known[name] = shp
        shapes = _infer_shapes(self, known)
        args_order = self.list_arguments()
        aux_order = self.list_auxiliary_states()
        arg_shapes = [shapes.get(("var", nm)) for nm in args_order]
        aux_shapes = [shapes.get(("var", nm)) for nm in aux_order]
        out_shapes = []
        for node, idx in self._entries:
            s = shapes.get((id(node), idx))
            out_shapes.append(s)
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [nm for nm, s in zip(args_order, arg_shapes) if s is None]
            raise MXNetError("infer_shape incomplete; unknown for args %s"
                             % missing)
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Propagate dtypes through the DAG (reference
        src/executor/infer_graph_attr_pass.cc:41-72 / op FInferType).

        Positional args pair with list_arguments(); kwargs name variables.
        Unknown variable inputs of an op adopt the op's promoted input
        dtype (the reference's same-type constraint), hooks override for
        ops with fixed signatures (Cast, BatchNorm's f32 stats, ...).
        """
        arg_names = self.list_arguments()
        known = {}
        for name, dt in zip(arg_names, args):
            if dt is not None:
                known[name] = _np.dtype(dt)
        for name, dt in kwargs.items():
            if dt is not None:
                known[name] = _np.dtype(dt)
        types = _infer_types(self, known)
        f32 = _np.dtype(_np.float32)
        arg_types = [types.get(("var", n), f32) for n in arg_names]
        out_types = []
        for (n, oi) in self._entries:
            key = ("var", n.name) if n.is_variable else (id(n), oi)
            out_types.append(types.get(key, f32))
        aux_types = [types.get(("var", n), f32)
                     for n in self.list_auxiliary_states()]
        return arg_types, out_types, aux_types

    # ----------------------------------------------------------------- eval
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        from ..executor import simple_bind
        return simple_bind(self, ctx, grad_req=grad_req, type_dict=type_dict,
                           group2ctx=group2ctx, **kwargs)

    # ---------------------------------------------------------------- serde
    def tojson(self):
        nodes = self._topo()
        for n in nodes:
            if not n.is_variable and "__flow__" not in n.attrs \
                    and _registry.get_or_none(n.op.name) is None:
                # e.g. fused subgraph nodes: their Operator is a closure
                # outside the registry, so the JSON could never load back
                raise MXNetError(
                    "cannot serialize symbol: op %r (node %r) is not in the "
                    "operator registry. Serialize the original symbol and "
                    "re-apply get_backend_symbol() after loading."
                    % (n.op.name, n.name))
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        row_ptr = [0]
        for n in nodes:
            attrs = {k: _attr_str(k, v) for k, v in n.params.items()}
            attrs.update({k: _attr_str(k, v) for k, v in n.attrs.items()
                          if k != "__flow__"})
            jn = {
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "inputs": [[nid[id(src)], oi, 0] for (src, oi) in n.inputs],
            }
            if "__flow__" in n.attrs:
                # control-flow node: embed the body sub-Symbol graph(s)
                # (reference nnvm subgraph serialization layout) plus the
                # slot metadata needed to rebuild the lax lowering
                subs, meta = n.attrs["__flow__"]
                jn["subgraphs"] = [json.loads(s.tojson()) for s in subs]
                attrs["__flow_meta__"] = json.dumps(meta)
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
            nout = 1 if n.is_variable else n.op.resolve_num_outputs(n.params)
            row_ptr.append(row_ptr[-1] + nout)
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        heads = [[nid[id(n)], oi, 0] for (n, oi) in self._entries]
        # the on-disk layout is the reference's
        # (python/mxnet/symbol/symbol.py save / src/nnvm graph serialization:
        # repr-string attr values, node_row_ptr, ["int", version] attrs) so a
        # prefix-symbol.json written here loads in reference MXNet and vice
        # versa (loader: load_json below, incl. legacy_json_util.cc upgrades)
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": row_ptr, "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10400]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------- gradient
    def gradient(self, wrt):  # kept for parity; bind-time autodiff is primary
        raise NotImplementedError("use executor.backward (jax.vjp at bind)")


def _sym_binary(op_name, scalar_op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return invoke_sym(op_name, [lhs, rhs], {})
    return invoke_sym(scalar_op, [lhs], {"scalar": float(rhs)})


def _sym_binary_r(op_name, rscalar_op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return invoke_sym(op_name, [rhs, lhs], {})
    return invoke_sym(rscalar_op, [lhs], {"scalar": float(rhs)})


def invoke_sym(op_name, inputs, params, name=None):
    """Create a graph node applying op to input symbols."""
    op = _registry.get(op_name)
    params = {k: v for k, v in params.items() if v is not None}
    entries = []
    for s in inputs:
        if isinstance(s, Symbol):
            if len(s._entries) == 1:
                entries.append(s._entries[0])
            else:
                entries.extend(s._entries)
        else:
            raise TypeError("symbol op %s expects Symbol inputs, got %r"
                            % (op_name, type(s)))
    # explicit names are used verbatim here: the user-facing codegen
    # (symbol/register.py) already routed them through the NameManager
    # (Prefix prepends to explicit names too, reference name.py); direct
    # invoke_sym callers (ONNX import, subgraph clone) need exact names
    if name is None:
        name = _auto_name(op_name.lower().lstrip("_") + "_")
    from ..attribute import current as _attr_current
    node = Node(op, name, entries, params,
                attrs=_attr_current().get(None) or None)
    # ops with aux outputs expose only the visible prefix to the graph
    # (BatchNorm: out [+ mean/var if output_mean_var] visible; updated moving
    # stats routed to aux storage) — reference FNumVisibleOutputs
    n_out = op.resolve_num_visible_outputs(params)
    return Symbol([(node, i) for i in range(n_out)]) if n_out > 1 \
        else Symbol([(node, 0)])


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    from ..attribute import current as _attr_current
    attrs = _attr_current().get(dict(attr or {}))
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        # canonical name: str(np.float16) is "<class 'numpy.float16'>",
        # which no consumer could parse
        attrs["__dtype__"] = str(_np.dtype(dtype))
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        # Initializer objects serialize via dumps() (json the registry can
        # recreate); plain strings pass through (reference attr contract)
        attrs["__init__"] = init.dumps() if hasattr(init, "dumps") \
            else str(init)
    if stype is not None:
        attrs["__storage_type__"] = stype
    attrs.update(kwargs)
    node = Node(None, name, [], {}, attrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


# ---------------------------------------------------------------------------
# shape inference over the DAG
# ---------------------------------------------------------------------------

def _infer_shapes(sym, known_var_shapes):
    """Forward shape propagation with parameter-shape hooks.

    Returns dict: ("var", name) -> shape for variables,
    (id(node), out_idx) -> shape for op outputs.
    """
    import jax

    shapes = {}
    for name, s in known_var_shapes.items():
        shapes[("var", name)] = tuple(s)
    nodes = sym._topo()
    for n in nodes:
        if n.is_variable:
            if ("var", n.name) not in shapes and "__shape__" in n.attrs:
                shapes[("var", n.name)] = tuple(n.attrs["__shape__"])
            continue
        in_shapes = []
        for (src, oi) in n.inputs:
            key = ("var", src.name) if src.is_variable else (id(src), oi)
            in_shapes.append(shapes.get(key))
        hook = getattr(n.op, "shape_hook", None)
        if hook is not None and any(s is None for s in in_shapes):
            try:
                completed = hook(in_shapes, n.params)
            except Exception as e:
                # surface hook bugs instead of silently degrading to
                # "infer_shape incomplete" (reference names the failing op)
                import warnings
                warnings.warn("shape hook for op %r (node %r) failed: %s: %s"
                              % (n.op.name, n.name, type(e).__name__, e))
                completed = in_shapes
            if completed:
                for (src, oi), s in zip(n.inputs, completed):
                    if s is None:
                        continue
                    key = ("var", src.name) if src.is_variable else (id(src), oi)
                    if shapes.get(key) is None:
                        shapes[key] = tuple(s)
                in_shapes = [tuple(s) if s is not None else None for s in completed]
        if any(s is None for s in in_shapes):
            continue
        try:
            structs = [jax.ShapeDtypeStruct(s, _np.float32) for s in in_shapes]
            out = jax.eval_shape(lambda *xs: n.op.fn(*xs, **n.params), *structs)
        except Exception:
            continue
        outs = out if isinstance(out, tuple) else (out,)
        for i, o in enumerate(outs):
            shapes[(id(n), i)] = tuple(o.shape)
    return shapes


def _infer_types(sym, known_var_types):
    """Forward dtype propagation with per-op hooks.

    Returns dict: ("var", name) -> dtype for variables,
    (id(node), out_idx) -> dtype for op outputs. Default rule: an op's
    outputs take the promotion (jnp.result_type) of its known input
    dtypes, and unknown VARIABLE inputs adopt that promoted dtype — the
    same-dtype constraint most reference ops register as FInferType.
    ``dtype_hook(in_dtypes, params) -> (in_dtypes, out_dtypes)`` overrides
    (Cast's target dtype, BatchNorm's pinned-f32 stats, ...).
    """
    import jax.numpy as jnp

    f32 = _np.dtype(_np.float32)
    types = {}
    for name, t in known_var_types.items():
        types[("var", name)] = _np.dtype(t)
    for n in sym._topo():
        if n.is_variable:
            if ("var", n.name) not in types and "__dtype__" in n.attrs:
                from ..base import normalize_dtype
                raw = n.attrs["__dtype__"]
                try:
                    types[("var", n.name)] = _np.dtype(raw)
                except TypeError:
                    types[("var", n.name)] = _np.dtype(normalize_dtype(raw))
            continue
        keys = [("var", s.name) if s.is_variable else (id(s), oi)
                for (s, oi) in n.inputs]
        in_dtypes = [types.get(k) for k in keys]
        hook = getattr(n.op, "dtype_hook", None)
        if hook is not None:
            completed, out_dtypes = hook(in_dtypes, n.params)
        else:
            knowns = [d for d in in_dtypes if d is not None]
            target = _np.dtype(jnp.result_type(*knowns)) if knowns else f32
            completed = [d if d is not None else target for d in in_dtypes]
            nout = n.op.resolve_num_outputs(n.params)
            out_dtypes = [target] * nout
        for k, (src, _), d in zip(keys, n.inputs, completed):
            if d is not None and src.is_variable and types.get(k) is None:
                types[k] = _np.dtype(d)
        for i, d in enumerate(out_dtypes):
            types[(id(n), i)] = _np.dtype(d)
    return types


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# MXNet's on-disk dtype enum (reference python/mxnet/base.py _DTYPE_MX_TO_NP)
_MX_DTYPE_CODE = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                  "int32": 4, "int8": 5, "int64": 6}
_MX_CODE_DTYPE = {v: k for k, v in _MX_DTYPE_CODE.items()}

# attr keys the reference hides as __key__ (c_api_symbolic.cc:41)
_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
                "mirror_stage")
# reference-era op params with no analog in the XLA lowering: dropping them
# changes nothing about the math (tuning/workspace knobs for cuDNN/MKLDNN)
_IGNORABLE_PARAMS = frozenset(
    ["workspace", "cudnn_tune", "cudnn_off", "key_var_num_args",
     # variadic-op arg count: implied by the JSON inputs list
     "num_args"])


def _attr_str(key, v):
    """Render one attr value the way reference JSON stores it (repr-string;
    __dtype__ as the dtype enum code)."""
    if key == "__dtype__":
        name = str(v)
        return str(_MX_DTYPE_CODE.get(name, name))
    if isinstance(v, str):
        return v
    return str(v)


def _attr_parse(raw):
    """Best-effort parse of one attr value: accepts this package's legacy
    json-encoded values AND the reference's repr-strings ("(3, 3)", "True",
    "64", "relu")."""
    if not isinstance(raw, str):
        return _untuple(raw)
    try:
        return _untuple(json.loads(raw))
    except (json.JSONDecodeError, ValueError):
        pass
    try:
        import ast
        return _untuple(ast.literal_eval(raw))
    except (ValueError, SyntaxError):
        return raw


def _user_attr_parse(key, raw):
    """User (dunder) attrs mostly stay strings; __shape__ and __dtype__
    are structural and get normalized for the shape/dtype inference."""
    if key == "__shape__":
        v = _attr_parse(raw)
        return tuple(v) if isinstance(v, (tuple, list)) else v
    if key == "__dtype__":
        v = _attr_parse(raw)
        if isinstance(v, int):
            return _MX_CODE_DTYPE.get(v, "float32")
        return raw
    if isinstance(raw, str):
        return raw
    return _untuple(raw)


_warned_params = set()


def _filter_params(opname, fn, params):
    """Drop params the lowering does not accept (reference-era backend
    knobs). Anything else unknown raises — silently eating a semantic
    param would load a different model."""
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return params
    if any(p.kind == inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return params
    known = set(sig.parameters)
    out = {}
    for k, v in params.items():
        if k in known:
            out[k] = v
        elif k in _IGNORABLE_PARAMS:
            if (opname, k) not in _warned_params:
                _warned_params.add((opname, k))
                logging.getLogger("mxnet_tpu").debug(
                    "load_json: dropping backend-tuning param %s.%s=%r",
                    opname, k, v)
        else:
            raise MXNetError(
                "load_json: op %r has no parameter %r (value %r). If this "
                "is a backend-tuning knob of the reference, add it to "
                "_IGNORABLE_PARAMS." % (opname, k, v))
    return out


def load_json(json_str):
    """Parse a symbol JSON — this package's own files or reference MXNet
    `prefix-symbol.json` files (format of python/mxnet/symbol save;
    upgrades of src/nnvm/legacy_json_util.cc:49-155: repr-string attrs
    under "attrs"/"attr"/"param", hidden keys like `weight_lr_mult`
    re-homed onto the matching input variable, dtype enum codes)."""
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        raw = jn.get("attrs", jn.get("attr", jn.get("param", {}))) or {}
        user = {k: _user_attr_parse(k, v) for k, v in raw.items()
                if k.startswith("__") and k.endswith("__")}
        # legacy bare hidden keys ("lr_mult") -> "__lr_mult__"
        # (UpgradeJSON_FixParsing, legacy_json_util.cc:49)
        for hk in _HIDDEN_KEYS:
            if hk in raw:
                user["__%s__" % hk] = _user_attr_parse("__%s__" % hk,
                                                       raw[hk])
        # own legacy format kept user attrs in a separate dict
        for k, v in jn.get("user_attrs", {}).items():
            user[k] = _user_attr_parse(k, v)
        inputs = [(nodes[i], jin[1] if len(jin) > 1 else 0)
                  for jin in jn["inputs"]
                  for i in [jin[0]]]
        if jn["op"] == "null":
            node = Node(None, jn["name"], [], {}, user)
        elif "subgraphs" in jn:
            # control-flow node: rebuild the lax lowering from the
            # embedded body graph(s) + metadata (contrib._build_*)
            from .contrib import rebuild_flow_node
            node = rebuild_flow_node(jn["op"], jn["subgraphs"],
                                     raw.get("__flow_meta__"),
                                     inputs, jn["name"])
            user.pop("__flow_meta__", None)
            node.attrs.update(user)  # user attrs survive the round-trip
        else:
            deferred = {}   # suffixed hidden keys: weight_lr_mult etc.
            params = {}
            for k, v in raw.items():
                if k.startswith("__") and k.endswith("__"):
                    continue
                hit = [hk for hk in _HIDDEN_KEYS
                       if k == hk or k.endswith("_" + hk)]
                if hit:
                    deferred[k] = (hit[0], v)
                    continue
                params[k] = _attr_parse(v)
            op = _registry.get(jn["op"])
            params = _filter_params(jn["op"], op.fn, params)
            node = Node(op, jn["name"], inputs, params, user)
            # re-home "argname_lr_mult" onto the input variable whose name
            # ends with "_argname" (legacy_json_util.cc:77-105 uses
            # FListInputNames; variable naming follows op_name + '_' + arg)
            for k, (hk, v) in deferred.items():
                if k == hk:
                    continue  # already handled as bare key above
                argname = k[: -(len(hk) + 1)]
                tgt = [src for src, _ in inputs
                       if src.is_variable
                       and src.name.endswith("_" + argname)]
                if len(tgt) == 1:
                    tgt[0].attrs["__%s__" % hk] = \
                        _user_attr_parse("__%s__" % hk, v)
                else:
                    node.attrs[k] = v  # keep; better than dropping
        nodes.append(node)
    entries = [(nodes[jh[0]], jh[1] if len(jh) > 1 else 0)
               for jh in data["heads"]]
    return Symbol(entries)


def _untuple(v):
    if isinstance(v, list):
        return tuple(v)
    return v
