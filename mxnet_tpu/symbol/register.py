"""Generate module-level symbolic op functions from the registry
(parity: python/mxnet/symbol/register.py codegen)."""
from __future__ import annotations

import sys

from ..ops import registry as _registry
from . import symbol as _symbol


def _make_op_func(op):
    variadic = len(op.input_names) == 0  # ops taking *data (Concat, stack)

    def fn(*args, name=None, **kwargs):
        node_name = _symbol._auto_name(
            op.name.lower().lstrip("_") + "_", name)
        if variadic:
            inputs = [a for a in args if isinstance(a, _symbol.Symbol)]
            sym_kwargs = [(k, v) for k, v in list(kwargs.items())
                          if isinstance(v, _symbol.Symbol)]
            for k, v in sym_kwargs:
                kwargs.pop(k)
                inputs.append(v)
            kwargs.pop("ctx", None)
            return _symbol.invoke_sym(op.name, inputs, kwargs, name=node_name)

        args, kwargs = op.bind_positional(args, kwargs)

        # named input slots: fill from positionals, then keywords, then
        # auto-create parameter variables the reference way
        # (e.g. Convolution(data) -> conv0_weight / conv0_bias variables;
        # SoftmaxOutput(net) -> <name>_label)
        slots = {}
        for slot_name, a in zip(op.input_names, args):
            if a is not None:
                if not isinstance(a, _symbol.Symbol):
                    raise TypeError("%s: input %r must be Symbol, got %r"
                                    % (op.name, slot_name, type(a)))
                slots[slot_name] = a
        for slot_name in op.input_names:
            if slot_name in kwargs and isinstance(kwargs[slot_name],
                                                  _symbol.Symbol):
                slots[slot_name] = kwargs.pop(slot_name)
        kwargs.pop("ctx", None)
        inputs = []
        for slot_name, optional in zip(op.input_names, op.input_optional):
            if slot_name in slots:
                inputs.append(slots[slot_name])
                continue
            if _should_autocreate(op, slot_name, optional, kwargs):
                if slot_name == "label":
                    vname = "%s_label" % node_name
                else:
                    vname = "%s_%s" % (node_name, slot_name)
                inputs.append(_symbol.Variable(vname))
            # else: trailing optional input omitted entirely
        return _symbol.invoke_sym(op.name, inputs, kwargs, name=node_name)
    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def _should_autocreate(op, slot_name, optional, params):
    if not optional:
        return True  # required array input with no symbol given -> variable
    if slot_name == "bias":
        return not params.get("no_bias", op.name == "Deconvolution")
    if slot_name == "label":
        return True  # loss heads auto-create their label variable
    if slot_name == "state_cell":
        return params.get("mode") == "lstm"
    if slot_name == "gamma" and params.get("act_type") == "prelu":
        return True
    return False


def populate(module_name):
    mod = sys.modules[module_name]
    for name in _registry.list_ops():
        op = _registry.get(name)
        setattr(mod, name, _make_op_func(op))
