"""``mx.sym.image`` namespace (reference symbol/image.py): attribute X
resolves to the registered ``_image_X`` operator."""
from ..ops.registry import namespaced_surface as _ns, list_ops as _list
from .register import _make_op_func as _mk

__getattr__, __dir__ = _ns(
    globals(), _mk,
    resolve=lambda n: "_image_" + n,
    listing=lambda: [n[len("_image_"):] for n in _list()
                     if n.startswith("_image_")])
