"""``mx.sym.random`` namespace (reference symbol/random.py): symbolic
sampling ops mirroring the ``mx.nd.random`` surface — shape-explicit
draws that become nodes in the graph and thread the trace key."""
from __future__ import annotations

from .symbol import Symbol, invoke_sym


def _shape(shape):
    if shape is None:
        return (1,)
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None,
            **kw):
    if isinstance(low, Symbol):
        return invoke_sym("_sample_uniform", [low, high],
                          {"shape": shape or (), "dtype": dtype})
    return invoke_sym("_random_uniform", [],
                      {"low": low, "high": high, "shape": _shape(shape),
                       "dtype": dtype})


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
           **kw):
    if isinstance(loc, Symbol):
        return invoke_sym("_sample_normal", [loc, scale],
                          {"shape": shape or (), "dtype": dtype})
    return invoke_sym("_random_normal", [],
                      {"loc": loc, "scale": scale, "shape": _shape(shape),
                       "dtype": dtype})


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None,
          **kw):
    if isinstance(alpha, Symbol):
        return invoke_sym("_sample_gamma", [alpha, beta],
                          {"shape": shape or (), "dtype": dtype})
    return invoke_sym("_random_gamma", [],
                      {"alpha": alpha, "beta": beta,
                       "shape": _shape(shape), "dtype": dtype})


def exponential(lam=1.0, shape=None, dtype="float32", ctx=None, **kw):
    return invoke_sym("_random_exponential", [],
                      {"lam": lam, "shape": _shape(shape), "dtype": dtype})


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, **kw):
    return invoke_sym("_random_poisson", [],
                      {"lam": lam, "shape": _shape(shape), "dtype": dtype})


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None,
                      **kw):
    return invoke_sym("_random_negative_binomial", [],
                      {"k": k, "p": p, "shape": _shape(shape),
                       "dtype": dtype})


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, **kw):
    return invoke_sym("_random_generalized_negative_binomial", [],
                      {"mu": mu, "alpha": alpha, "shape": _shape(shape),
                       "dtype": dtype})


def randint(low, high, shape=None, dtype="int32", ctx=None, **kw):
    return invoke_sym("_random_randint", [],
                      {"low": low, "high": high, "shape": _shape(shape),
                       "dtype": dtype})


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return invoke_sym("_sample_multinomial", [data],
                      {"shape": shape or (), "get_prob": get_prob,
                       "dtype": dtype})


def shuffle(data, **kw):
    return invoke_sym("_shuffle", [data], {})
