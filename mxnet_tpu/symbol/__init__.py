"""Symbolic API package (parity: python/mxnet/symbol/)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     invoke_sym)
from . import register as _register
from . import linalg
from . import contrib
from . import random
from . import sparse
from . import image
from . import op
from . import _internal

_register.populate(__name__)

# zeros/ones for symbol graphs
def zeros(shape, dtype="float32", **kw):
    return invoke_sym("_zeros", [], {"shape": tuple(shape), "dtype": dtype})


def ones(shape, dtype="float32", **kw):
    return invoke_sym("_ones", [], {"shape": tuple(shape), "dtype": dtype})
