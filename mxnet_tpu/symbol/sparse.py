"""``mx.sym.sparse`` namespace (reference symbol/sparse.py — generated
sparse operators). Resolves attribute X to the registered ``_sparse_X``
op, falling back to the plain name for ops shared with the dense
surface (dot, retain-style helpers)."""
from ..ops.registry import namespaced_surface as _ns, list_ops as _list, \
    get_or_none as _get
from .register import _make_op_func as _mk


def _resolve(n):
    if n.startswith("_"):
        return None
    if _get("_sparse_" + n) is not None:
        return "_sparse_" + n
    return n


__getattr__, __dir__ = _ns(
    globals(), _mk, resolve=_resolve,
    listing=lambda: [n[len("_sparse_"):] for n in _list()
                     if n.startswith("_sparse_")])
