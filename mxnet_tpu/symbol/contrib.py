"""``mx.sym.contrib`` namespace (reference python surface:
python/mxnet/symbol/contrib.py): symbolic entry points for every
registered ``_contrib_*`` operator, resolved lazily from the operator
registry, plus the symbolic control-flow trio ``foreach`` /
``while_loop`` / ``cond`` (reference contrib.py:95-740 building
`_foreach`/`_while_loop`/`_cond` subgraph nodes,
src/operator/control_flow.cc:1255/1316/1378).

TPU-native control-flow design: the reference cuts the body into an
nnvm subgraph executed by a dedicated C++ op with hand-written
gradients. Here the body is traced into a sub-Symbol, evaluated by the
same pure interpreter the executor jits (`executor._graph_eval_fn`),
and the step node's fn lowers to ``lax.scan`` / a masked fixed-trip
scan / ``lax.cond`` — so the compiled graph gets real XLA control flow
and the gradient falls out of ``jax.vjp``, no custom backward.
(while_loop uses a masked scan rather than ``lax.while_loop`` because
reverse-mode autodiff cannot cross while_loop and ``max_iterations`` is
mandatory anyway.)

Construction is split trace/build: the public functions trace the body
into a sub-Symbol plus a metadata dict, and ``_build_*`` turns
(subgraphs, meta, inputs) into the node. JSON serde round-trips through
the same split — ``tojson`` embeds the sub-Symbol graphs in the node's
``subgraphs`` field (the reference's subgraph wire layout) with the
metadata as a node attr, and ``load_json`` rebuilds via ``_build_*`` —
so control-flow models checkpoint like any other (reference
nnvm::Symbol subgraph serialization).

Aux states (e.g. BatchNorm moving stats) used inside a body stay
classified auxiliary in the outer graph and are read-only within the
loop.
"""
from __future__ import annotations

import itertools
import json as _json

from ..base import MXNetError
from ..ops.registry import contrib_surface as _contrib_surface, Operator
from .symbol import Symbol, Variable, Group, Node, _auto_name

_uid = itertools.count()


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _unwrap(lst, single):
    return lst[0] if single else lst


def _one_entry(sym, what):
    if not isinstance(sym, Symbol):
        raise TypeError("%s must be a Symbol, got %r" % (what, type(sym)))
    if len(sym._entries) != 1:
        raise MXNetError("%s must be a single-output Symbol" % what)
    return sym._entries[0]


def _trace_subgraph(out_syms, placeholder_names):
    """Group outputs into a sub-Symbol; split its variables into
    (free arg nodes, aux nodes) excluding the placeholders."""
    sub = Group(out_syms)
    aux_names = set(sub.list_auxiliary_states())
    free_nodes = [n for n in sub._topo()
                  if n.is_variable and n.name not in placeholder_names]
    arg_nodes = [n for n in free_nodes if n.name not in aux_names]
    aux_nodes = [n for n in free_nodes if n.name in aux_names]
    return sub, arg_nodes, aux_nodes


def _has_random(sub):
    return any(n.op.is_random for n in sub._topo() if not n.is_variable)


def _flow_node(op_name, fn, n_outputs, input_entries, name, is_random,
               shape_hook=None, aux_slots=(), flow_payload=None):
    op = Operator(op_name, fn, num_outputs=n_outputs, is_random=is_random)
    op.shape_hook = shape_hook
    # aux slots keep BatchNorm-style moving stats classified as auxiliary
    # states in the OUTER graph too (read-only inside the body), instead
    # of silently becoming trainable arguments — same wiring as fused
    # subgraph nodes (subgraph.py)
    op.aux_inputs = tuple(aux_slots)
    node = Node(op, _auto_name(op_name.strip("_") + "_", name),
                list(input_entries), {})
    if flow_payload is not None:
        # consumed by tojson (serialized as node "subgraphs" + meta attr)
        # and skipped by attr_dict; see _FLOW_REBUILD for the load side
        node.attrs["__flow__"] = flow_payload
    return Symbol([(node, i) for i in range(n_outputs)])


def _check_single(syms, what):
    for s in syms:
        _one_entry(s, what)
    return syms


def _subgraph_shape_hook(sub, slot_names, slot_slice_axis0):
    """Back-infer unknown loop-node input shapes by running the body
    sub-Symbol's own partial shape inference (the reference's subgraph
    FInferShape pass, control_flow.cc ForeachShape).

    ``slot_names``: sub-graph variable name per node input slot;
    ``slot_slice_axis0``: slots whose node-level shape carries a leading
    scan axis the per-step subgraph doesn't see."""
    slot_slice_axis0 = set(slot_slice_axis0)

    def hook(in_shapes, params):
        known = {}
        for i, (nm, s) in enumerate(zip(slot_names, in_shapes)):
            if s is None:
                continue
            known[nm] = tuple(s[1:]) if i in slot_slice_axis0 else tuple(s)
        try:
            arg_shapes, _, aux_shapes = sub.infer_shape_partial(**known)
        except Exception:
            return in_shapes
        inferred = dict(zip(sub.list_arguments(), arg_shapes))
        inferred.update(zip(sub.list_auxiliary_states(), aux_shapes))
        out = []
        for i, (nm, s) in enumerate(zip(slot_names, in_shapes)):
            if s is not None:
                out.append(s)
                continue
            got = inferred.get(nm)
            if got is not None and i in slot_slice_axis0:
                got = None  # can't recover the scan length from a slice
            out.append(tuple(got) if got is not None else None)
        return out

    return hook


# ---------------------------------------------------------------------------
# builders: (subgraphs, meta, input entries) -> flow-node Symbol.
# The public trace functions call these directly; load_json rebuilds
# through the same path (_FLOW_REBUILD).
# ---------------------------------------------------------------------------

def _build_foreach(sub, meta, entries, name):
    import jax
    from jax import lax
    from ..executor import _graph_eval_fn
    from .. import random as _random

    n_data, n_st, n_out = meta["n_data"], meta["n_st"], meta["n_out"]
    d_names, s_names = meta["d_names"], meta["s_names"]
    f_names, a_names = meta["f_names"], meta["a_names"]
    eval_fn = _graph_eval_fn(sub)

    def fn(*args, _training=True):
        datas = args[:n_data]
        st0 = args[n_data:n_data + n_st]
        free = dict(zip(f_names, args[n_data + n_st:
                                      n_data + n_st + len(f_names)]))
        aux = dict(zip(a_names, args[n_data + n_st + len(f_names):]))
        key0 = _random.next_key()

        def step(carry, xs):
            key, sts = carry[0], carry[1:]
            key, sub_key = jax.random.split(key)
            vals = dict(free)
            vals.update(zip(d_names, xs))
            vals.update(zip(s_names, sts))
            outputs, _ = eval_fn(vals, aux, sub_key, _training)
            return ((key,) + tuple(outputs[n_out:]),
                    tuple(outputs[:n_out]))

        final, ys = lax.scan(step, (key0,) + tuple(st0), tuple(datas))
        return tuple(ys) + tuple(final[1:])

    hook = _subgraph_shape_hook(sub, d_names + s_names + f_names + a_names,
                                range(n_data))
    aux0 = n_data + n_st + len(f_names)
    return _flow_node("_foreach", fn, n_out + n_st, entries, name,
                      _has_random(sub), shape_hook=hook,
                      aux_slots=range(aux0, aux0 + len(a_names)),
                      flow_payload=([sub], meta))


def _build_while(sub, meta, entries, name):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..executor import _graph_eval_fn
    from .. import random as _random

    n_v, n_out = meta["n_v"], meta["n_out"]
    max_iterations = meta["max_iterations"]
    v_names, f_names, a_names = (meta["v_names"], meta["f_names"],
                                 meta["a_names"])
    eval_fn = _graph_eval_fn(sub)

    def fn(*args, _training=True):
        # fixed-trip lax.scan with an active mask, NOT lax.while_loop:
        # reverse-mode jax.vjp cannot differentiate through while_loop,
        # and max_iterations is mandatory anyway. Iterations past the
        # predicate's first False keep the carry frozen and record zeros
        # (the reference's zero-padded step outputs). cond and body come
        # from ONE subgraph evaluation per step, so a random predicate
        # decides on exactly the values the carry commits.
        v0 = args[:n_v]
        free = dict(zip(f_names, args[n_v:n_v + len(f_names)]))
        aux = dict(zip(a_names, args[n_v + len(f_names):]))
        key0 = _random.next_key()

        def step(carry, _):
            key, active, vars_ = carry
            key, sub_key = jax.random.split(key)
            vals = dict(free)
            vals.update(zip(v_names, vars_))
            outputs, _ = eval_fn(vals, aux, sub_key, _training)
            c = jnp.squeeze(outputs[0]).astype(bool)
            step_outs = tuple(outputs[1:1 + n_out])
            nxt = tuple(outputs[1 + n_out:])
            cont = jnp.logical_and(active, c)
            new_vars = tuple(
                jnp.where(cont, n_, v_) for n_, v_ in zip(nxt, vars_))
            recorded = tuple(
                jnp.where(cont, o, jnp.zeros_like(o)) for o in step_outs)
            return (key, cont, new_vars), recorded

        (_, _, fin), ys = lax.scan(
            step, (key0, jnp.bool_(True), tuple(v0)), None,
            length=max_iterations)
        return tuple(ys) + tuple(fin)

    hook = _subgraph_shape_hook(sub, v_names + f_names + a_names, ())
    aux0 = n_v + len(f_names)
    return _flow_node("_while_loop", fn, n_out + n_v, entries, name,
                      _has_random(sub), shape_hook=hook,
                      aux_slots=range(aux0, aux0 + len(a_names)),
                      flow_payload=([sub], meta))


def _build_cond(sub_t, sub_e, meta, entries, name):
    import jax.numpy as jnp
    from jax import lax
    from ..executor import _graph_eval_fn
    from .. import random as _random

    n_out = meta["n_out"]
    ft, at, fe, ae = meta["ft"], meta["at"], meta["fe"], meta["ae"]
    nt, nat, ne, nae = len(ft), len(at), len(fe), len(ae)
    eval_t = _graph_eval_fn(sub_t)
    eval_e = _graph_eval_fn(sub_e)

    def fn(pred_v, *args, _training=True):
        vt = dict(zip(ft, args[:nt]))
        xt = dict(zip(at, args[nt:nt + nat]))
        ve = dict(zip(fe, args[nt + nat:nt + nat + ne]))
        xe = dict(zip(ae, args[nt + nat + ne:]))
        key = _random.next_key()

        def t(_):
            outs, _aux = eval_t(vt, xt, key, _training)
            return tuple(outs)

        def e(_):
            outs, _aux = eval_e(ve, xe, key, _training)
            return tuple(outs)

        return lax.cond(jnp.squeeze(pred_v).astype(bool), t, e, None)

    aux_slots = list(range(1 + nt, 1 + nt + nat)) \
        + list(range(1 + nt + nat + ne, 1 + nt + nat + ne + nae))
    return _flow_node("_cond", fn, n_out, entries, name,
                      _has_random(sub_t) or _has_random(sub_e),
                      aux_slots=aux_slots,
                      flow_payload=([sub_t, sub_e], meta))


_FLOW_REBUILD = {
    "_foreach": lambda subs, meta, entries, name:
        _build_foreach(subs[0], meta, entries, name),
    "_while_loop": lambda subs, meta, entries, name:
        _build_while(subs[0], meta, entries, name),
    "_cond": lambda subs, meta, entries, name:
        _build_cond(subs[0], subs[1], meta, entries, name),
}


def rebuild_flow_node(op_name, sub_jsons, meta_raw, input_entries, name):
    """load_json hook: reconstruct a control-flow node from its embedded
    subgraph JSONs + metadata attr."""
    from .symbol import load_json
    if op_name not in _FLOW_REBUILD:
        raise MXNetError(
            "node %r carries subgraphs but op %r has no rebuild rule "
            "here (reference nnvm subgraph ops beyond "
            "_foreach/_while_loop/_cond are unsupported)"
            % (name, op_name))
    if meta_raw is None:
        raise MXNetError(
            "control-flow node %r (%s) has no __flow_meta__ attr: this "
            "JSON was serialized by reference MXNet's nnvm subgraph "
            "format, whose C++ slot layout we don't reconstruct — "
            "re-export the model through this package's tojson()"
            % (name, op_name))
    subs = [load_json(_json.dumps(sj)) for sj in sub_jsons]
    meta = _json.loads(meta_raw) if isinstance(meta_raw, str) else meta_raw
    sym = _FLOW_REBUILD[op_name](subs, meta, input_entries, name)
    node = sym._entries[0][0]
    # serialized names load VERBATIM (like every other node kind) — the
    # builder routed `name` through the NameManager, which would prefix
    # it inside an active mx.name.Prefix scope and desync name-keyed
    # consumers from the checkpoint
    node.name = name
    return node  # caller re-wraps entries


# ---------------------------------------------------------------------------
# public trace functions
# ---------------------------------------------------------------------------

def foreach(body, data, init_states, name=None):
    """Symbolic scan: run ``body(data_slice, states)`` over axis 0 of
    ``data``, threading states (reference sym.contrib.foreach).
    Returns (outputs, final_states) with the body's structure."""
    data_list, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    uid = next(_uid)
    ph_data = [Variable("_foreach%d_data%d" % (uid, i))
               for i in range(len(data_list))]
    ph_states = [Variable("_foreach%d_state%d" % (uid, i))
                 for i in range(len(states))]
    outs, fin = body(_unwrap(ph_data, single_data),
                     _unwrap(ph_states, single_state))
    out_list, single_out = _as_list(outs)
    fin_list, _ = _as_list(fin)
    if len(fin_list) != len(states):
        raise MXNetError(
            "foreach body returned %d states, expected %d"
            % (len(fin_list), len(states)))
    _check_single(out_list, "foreach body output")
    _check_single(fin_list, "foreach body state")
    d_names = [s.name for s in ph_data]
    s_names = [s.name for s in ph_states]
    sub, arg_nodes, aux_nodes = _trace_subgraph(
        out_list + fin_list, set(d_names + s_names))
    meta = {"n_data": len(data_list), "n_st": len(states),
            "n_out": len(out_list), "d_names": d_names,
            "s_names": s_names,
            "f_names": [n.name for n in arg_nodes],
            "a_names": [n.name for n in aux_nodes]}
    entries = [_one_entry(s, "foreach data") for s in data_list] \
        + [_one_entry(s, "foreach state") for s in states] \
        + [(n, 0) for n in arg_nodes] + [(n, 0) for n in aux_nodes]
    res = _build_foreach(sub, meta, entries, name)
    n_out, n_st = meta["n_out"], meta["n_st"]
    out = _unwrap([res[i] for i in range(n_out)], single_out)
    fin_states = _unwrap([res[n_out + i] for i in range(n_st)],
                         single_state)
    return out, fin_states


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """Symbolic while: run ``func`` while ``cond`` holds, up to
    ``max_iterations``; step outputs are stacked and zero-padded to
    max_iterations (reference sym.contrib.while_loop)."""
    if max_iterations is None:
        raise ValueError("max_iterations is required")
    lvars, single = _as_list(loop_vars)
    uid = next(_uid)
    ph = [Variable("_while%d_var%d" % (uid, i)) for i in range(len(lvars))]
    cond_sym = cond(*ph)
    step_out, new_vars = func(*ph)
    out_list, single_out = _as_list(step_out)
    nv_list, _ = _as_list(new_vars)
    if len(nv_list) != len(lvars):
        raise MXNetError("while_loop func returned %d loop_vars, "
                         "expected %d" % (len(nv_list), len(lvars)))
    _check_single([cond_sym], "while_loop cond output")
    _check_single(out_list, "while_loop step output")
    _check_single(nv_list, "while_loop loop_var")
    v_names = [s.name for s in ph]
    sub, arg_nodes, aux_nodes = _trace_subgraph(
        [cond_sym] + out_list + nv_list, set(v_names))
    meta = {"n_v": len(lvars), "n_out": len(out_list),
            "max_iterations": int(max_iterations), "v_names": v_names,
            "f_names": [n.name for n in arg_nodes],
            "a_names": [n.name for n in aux_nodes]}
    entries = [_one_entry(s, "while_loop var") for s in lvars] \
        + [(n, 0) for n in arg_nodes] + [(n, 0) for n in aux_nodes]
    res = _build_while(sub, meta, entries, name)
    n_out, n_v = meta["n_out"], meta["n_v"]
    out = _unwrap([res[i] for i in range(n_out)], single_out)
    fin = _unwrap([res[n_out + i] for i in range(n_v)], single)
    return out, fin


def cond(pred, then_func, else_func, name=None):
    """Symbolic branch: then_func() or else_func() by scalar ``pred``
    (reference sym.contrib.cond). Both branches must produce the same
    output structure."""
    then_out, single_then = _as_list(then_func())
    else_out, single_else = _as_list(else_func())
    if len(then_out) != len(else_out) or single_then != single_else:
        raise MXNetError("cond branches must return the same structure")
    _check_single(then_out, "cond then output")
    _check_single(else_out, "cond else output")
    sub_t, arg_t, aux_t = _trace_subgraph(then_out, set())
    sub_e, arg_e, aux_e = _trace_subgraph(else_out, set())
    meta = {"n_out": len(then_out),
            "ft": [n.name for n in arg_t], "at": [n.name for n in aux_t],
            "fe": [n.name for n in arg_e], "ae": [n.name for n in aux_e]}
    entries = [_one_entry(pred, "cond pred")] \
        + [(n, 0) for n in arg_t] + [(n, 0) for n in aux_t] \
        + [(n, 0) for n in arg_e] + [(n, 0) for n in aux_e]
    res = _build_cond(sub_t, sub_e, meta, entries, name)
    return _unwrap([res[i] for i in range(meta["n_out"])], single_then)


def _make_contrib_fn(op):
    from . import register as _register
    return _register._make_op_func(op)


__getattr__, __dir__ = _contrib_surface(globals(), _make_contrib_fn)
