"""``mx.sym.contrib`` namespace: symbolic entry points for every
registered ``_contrib_*`` operator (reference python surface:
python/mxnet/symbol/contrib.py code-generation), resolved lazily from the
operator registry."""
from __future__ import annotations

from ..ops.registry import contrib_surface as _contrib_surface


def _make_contrib_fn(op):
    from . import register as _register
    return _register._make_op_func(op)


__getattr__, __dir__ = _contrib_surface(globals(), _make_contrib_fn)
