"""``mx.sym.contrib`` namespace: symbolic entry points for every
registered ``_contrib_*`` operator (reference python surface:
python/mxnet/symbol/contrib.py code-generation), resolved lazily from the
operator registry."""
from __future__ import annotations


def __getattr__(name):
    from ..ops import registry as _registry
    from . import register as _register
    op = _registry.get_or_none("_contrib_" + name)
    if op is None:
        raise AttributeError(
            "mxnet_tpu.symbol.contrib has no attribute %r" % name)
    fn = _register._make_op_func(op)
    fn.__name__ = name
    globals()[name] = fn
    return fn
