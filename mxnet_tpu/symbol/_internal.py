"""``mx.sym._internal`` namespace (reference symbol/_internal.py)."""
from ..ops.registry import namespaced_surface as _ns, list_ops as _list
from .register import _make_op_func as _mk

__getattr__, __dir__ = _ns(
    globals(), _mk,
    resolve=lambda n: n if n.startswith("_") else None,
    listing=lambda: [n for n in _list() if n.startswith("_")])
