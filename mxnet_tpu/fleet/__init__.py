"""Fleet serving tier: router, replica registry, and supervisor.

The layer above one ``serve/`` process (ROADMAP item 1): N replica
servers register into a :class:`ReplicaRegistry`, a :class:`Router`
load-balances ``/v1/predict`` least-loaded on perfmodel-derived cost
estimates and routes ``/v1/generate`` session-affine with transparent
cursor migration off dead/draining replicas, and a
:class:`ReplicaSupervisor` keeps replica processes alive with the same
capped-jittered-backoff restart discipline ``tools/launch.py`` gives
training workers. Blue/green multi-version hosting and int8 canary
auto-rollback ride on the registry's ``(model, version)`` identity.

The router itself is highly available: a :class:`FleetJournal`
write-ahead logs every registry mutation and generate hop cursor, a
warm standby (``tools/route.py --standby``) tails it and promotes on
lease expiry, and fencing epochs (:mod:`mxnet_tpu.fleet.fencing`) keep
a revived stale primary from split-braining the fleet. The journal no
longer needs shared storage: a :class:`JournalReplicator` standby
(``--standby --replicate-from URL``) streams snapshot + WAL segments
over the primary's own HTTP front end into a local replica —
CRC re-verified, epoch-fenced, seq-gap-resynced — and promotes from
that even when the primary's disk dies with it. When the primary's
*own* journal disk fails mid-flight, the router degrades instead of
dying: control-plane mutations return 503 + Retry-After
(:class:`JournalDegraded`) while routed traffic keeps flowing, and a
recovered disk exits degraded mode without a restart.

The fleet is elastic: an :class:`Autoscaler` per model watches the
registry's perfmodel-derived demand signals and asks the supervisor to
launch or drain replicas under a hysteresis + cooldown + break-even
policy, journaling every decision so a promoted standby inherits the
scaling state (:mod:`mxnet_tpu.fleet.autoscale`). The router also
records each replica's parameter-layout fingerprint
(:mod:`mxnet_tpu.parallel.layout`) and refuses traffic splits that
would mix layouts.

Entry points: ``tools/route.py`` (router CLI), ``tools/serve.py
--register`` (replica side). docs/fleet.md is the operator tour.
"""
from __future__ import annotations

from . import fencing
from .autoscale import AutoscalePolicy, Autoscaler
from .journal import (FleetJournal, FleetState, JournalTailer,
                      LeaseMonitor)
from .registry import Replica, ReplicaAnnouncer, ReplicaRegistry
from .replicate import (JournalReplicator, ReplicationError,
                        StaleSourceError)
from .router import (JournalDegraded, NoReplica, Router,
                     RouterHTTPFrontEnd, route_http)
from .supervisor import ReplicaSpec, ReplicaSupervisor, backoff_delay

__all__ = [
    "Replica", "ReplicaAnnouncer", "ReplicaRegistry",
    "NoReplica", "JournalDegraded", "Router", "RouterHTTPFrontEnd",
    "route_http",
    "ReplicaSpec", "ReplicaSupervisor", "backoff_delay",
    "AutoscalePolicy", "Autoscaler",
    "FleetJournal", "FleetState", "JournalTailer", "LeaseMonitor",
    "JournalReplicator", "ReplicationError", "StaleSourceError",
    "fencing",
]
