"""Fleet router: the tier above one ``serve/`` process.

One router process fronts N replica servers (``tools/serve.py
--register``) and speaks the *same* client protocol they do, so a
client pointed at a replica yesterday points at the router today:

* ``POST /v1/predict`` — **least-loaded**: each replica's heartbeat
  carries ``load_s`` (estimated seconds of queued work) and ``unit_s``
  (estimated seconds per marginal request), both derived from
  ``perfmodel.roofline_seconds`` on the replica (the identical cost
  tables its own admission control uses); the router picks the minimum
  ``load_s + inflight * unit_s`` and retries rejections/deaths on the
  next-best replica.
* ``POST /v1/generate`` — **session-affine with cursor migration**: a
  decode session's KV pages live on one replica, so the router parks
  the whole generation there — but forwards it in *hops* of at most
  ``MXNET_FLEET_HOP_TOKENS`` tokens, which means it always holds a
  resume point (``prompt + tokens so far``, the exact shape of the
  PR-9 eviction cursor). When the owner dies mid-hop or drains
  (eviction cursor in a 429), the router resubmits on a survivor and
  stitches the tail; position-keyed sampling makes the stitched stream
  **bitwise identical** to an uninterrupted run, which the migration
  test asserts token-for-token.
* blue/green + canary: replicas register under ``(model, version)``;
  ``/admin/split`` sets version weights, ``/admin/canary`` starts a
  canary at a small split with the PR-10 accuracy-probe delta as the
  rollback signal (``/admin/canary/report``; budget
  ``MXNET_QUANT_ACCURACY_BUDGET``), and rollback is router-side only —
  new traffic stops, in-flight requests on the canary finish — so zero
  requests drop.
* ``GET /metrics`` — federation: every live replica's exposition
  merged under ``replica="<id>"`` labels plus the router's own
  ``fleet/*`` series (``telemetry/federate.py``).

Import-light by design (stdlib + config + telemetry): the router never
runs model code or touches a device — replicas own the accelerators;
the router holds only cursors, counters, and the registry.
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..base import MXNetError
from ..config import flags
from .. import telemetry
from ..telemetry import federate
from .registry import ReplicaRegistry

__all__ = ["Router", "NoReplica", "RouterHTTPFrontEnd", "route_http"]


class NoReplica(MXNetError):
    """No ready replica can take this request."""


class Router:
    """Routing core; :class:`RouterHTTPFrontEnd` is the wire skin.

    Public entry points (``route_predict``/``route_generate``) return
    ``(status_code, payload_dict, extra_headers)`` so the HTTP handler
    and in-process tests share one code path."""

    def __init__(self, registry=None, hop_tokens=None, retry_limit=None,
                 proxy_timeout_s=None, rng=None):
        self.registry = registry or ReplicaRegistry()
        self.hop_tokens = (flags.fleet_hop_tokens if hop_tokens is None
                           else int(hop_tokens))
        self.retry_limit = (flags.fleet_retry_limit if retry_limit is None
                            else int(retry_limit))
        self.proxy_timeout_s = (flags.fleet_proxy_timeout_s
                                if proxy_timeout_s is None
                                else float(proxy_timeout_s))
        self._rng = rng or random.Random(0x5EED)
        self._lock = threading.Lock()
        self.splits = {}     # model -> {version: weight} (normalized)
        self.canaries = {}   # model -> canary record dict
        reg = telemetry.default_registry()
        self._c_requests = reg.counter(
            "fleet/requests", "Requests routed, by kind and outcome.")
        self._c_retries = reg.counter(
            "fleet/retries", "Re-routes after a replica rejected/died.")
        self._c_hops = reg.counter(
            "fleet/generate_hops", "Generate hops forwarded to replicas.")
        self._c_migrations = reg.counter(
            "fleet/migrations",
            "Decode sessions moved to a surviving replica via cursor.")
        self._c_deaths = reg.counter(
            "fleet/replica_deaths", "Replicas marked dead by the router.")
        self._c_rollbacks = reg.counter(
            "fleet/canary_rollbacks", "Canaries auto-rolled back.")
        self._g_ready = reg.gauge(
            "fleet/replicas_ready", "Replicas currently in rotation.")

    # -- proxy plumbing -----------------------------------------------------
    def _call(self, url, payload, timeout_s):
        """POST json; returns (status, body_dict, headers). Connection
        failures raise (the caller marks the replica dead)."""
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, json.loads(r.read().decode() or "{}"), \
                    dict(r.headers)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode() or "{}")
            except ValueError:
                body = {"error": "replica returned unparseable body"}
            return e.code, body, dict(e.headers)

    def _scrape(self, url, timeout_s=5.0):
        req = urllib.request.Request(
            url + "/metrics?format=prometheus",
            headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.read().decode("utf-8")

    # -- replica selection --------------------------------------------------
    def _resolve_model(self, model, cands):
        if model is not None:
            return str(model)
        names = sorted({r.model for r in cands})
        if len(names) == 1:
            return names[0]
        raise NoReplica(
            "fleet: %d models hosted (%s); the request must name one "
            'with {"model": ...}' % (len(names), names))

    def _choose_version(self, model, by_version):
        """Weighted version choice per the model's traffic split; falls
        back to every ready version (availability beats policy) when
        the split names none of them."""
        with self._lock:
            split = dict(self.splits.get(model) or {})
        if split:
            avail = {v: w for v, w in split.items()
                     if v in by_version and w > 0.0}
            if avail:
                total = sum(avail.values())
                x = self._rng.random() * total
                for v, w in sorted(avail.items()):
                    x -= w
                    if x <= 0:
                        return v
                return sorted(avail)[-1]
            # a split is a statement of intent: versions weighted 0 (a
            # rolled-back canary) stay out even when the split's chosen
            # versions are all down — unless NOTHING else is ready.
            allowed = [v for v in by_version if v not in split]
            if allowed:
                return None if len(allowed) > 1 else allowed[0]
        return None    # no preference: least-loaded across all versions

    def _pick(self, model=None, version=None, mode=None, exclude=()):
        cands = self.registry.routable(model=model, mode=mode)
        cands = [r for r in cands if r.id not in exclude]
        self._g_ready.set(len(cands))
        if not cands:
            raise NoReplica(
                "fleet: no ready %s replica%s%s (check /fleet for "
                "replica states)"
                % (mode or "", " for model %r" % model if model else "",
                   " excluding %s" % sorted(exclude) if exclude else ""))
        model = self._resolve_model(model, cands)
        cands = [r for r in cands if r.model == model]
        if not cands:
            raise NoReplica("fleet: no ready replica for model %r" % model)
        if version is None:
            by_version = {}
            for r in cands:
                by_version.setdefault(r.version, []).append(r)
            chosen = self._choose_version(model, by_version)
            if chosen is not None:
                cands = by_version[chosen]
        else:
            cands = [r for r in cands if r.version == str(version)]
            if not cands:
                raise NoReplica(
                    "fleet: no ready replica for model %r version %r"
                    % (model, version))
        # least-loaded on the perfmodel-derived heartbeat score;
        # `served` tie-breaks into round-robin on a cold fleet
        return min(cands, key=lambda r: (r.score(), r.served, r.id))

    # -- predict path -------------------------------------------------------
    def route_predict(self, payload):
        model = payload.get("model")
        version = payload.get("version")
        body = {k: v for k, v in payload.items()
                if k not in ("model", "version")}
        timeout_s = self.proxy_timeout_s
        if payload.get("timeout_ms"):
            timeout_s = payload["timeout_ms"] / 1e3 + 5.0
        tried = set()
        last = None
        for attempt in range(self.retry_limit + 1):
            try:
                rep = self._pick(model, version, "predict", exclude=tried)
            except NoReplica as e:
                if last is not None:
                    self._c_requests.inc(kind="predict", outcome="rejected")
                    return last
                self._c_requests.inc(kind="predict", outcome="no_replica")
                return 503, {"error": str(e)}, {}
            tried.add(rep.id)
            if attempt > 0:
                self._c_retries.inc(kind="predict")
            self.registry.note_inflight(rep.id, +1)
            try:
                status, out, headers = self._call(
                    rep.url + "/v1/predict", body, timeout_s)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                self.registry.mark_dead(
                    rep.id, "predict proxy failed: %s" % e)
                self._c_deaths.inc()
                continue
            finally:
                self.registry.note_inflight(rep.id, -1)
            if status == 200:
                out["replica"] = rep.id
                out["version"] = rep.version
                self._c_requests.inc(kind="predict", outcome="ok")
                return 200, out, {}
            if status in (429, 503):
                # busy/draining: remember the hint, try the next-best
                extra = {}
                if headers.get("Retry-After"):
                    extra["Retry-After"] = headers["Retry-After"]
                if status == 503:
                    self.registry.mark_not_ready(rep.id, "answered 503")
                last = (status, out, extra)
                continue
            # 400/500/504: the replica answered definitively
            self._c_requests.inc(kind="predict", outcome="error")
            return status, out, {}
        self._c_requests.inc(kind="predict", outcome="rejected")
        return last or (503, {"error": "fleet: every replica rejected "
                                       "this request"}, {})

    # -- generate path ------------------------------------------------------
    def _partial_cursor(self, prompt, tokens, remaining):
        # same shape GenerateSession._cursor emits, so a client can
        # resubmit a router-partial exactly like a replica eviction
        return {"prompt": list(prompt), "generated": list(tokens),
                "resume_prompt": list(prompt) + list(tokens),
                "remaining_tokens": int(remaining)}

    def route_generate(self, payload):
        model = payload.get("model")
        version = payload.get("version")
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return 400, {"error": 'body must be {"prompt": [ids], ...}'}, {}
        # mirror tools/serve.py's --max-new-tokens default: hop chunking
        # needs a concrete total budget
        remaining = int(payload.get("max_new_tokens") or 64)
        temperature = payload.get("temperature", 0.0)
        seed = payload.get("seed", 0)
        deadline = None
        if payload.get("timeout_ms"):
            deadline = time.monotonic() + payload["timeout_ms"] / 1e3
        hop = self.hop_tokens
        t0 = time.monotonic()
        tokens = []
        cur_prompt = [int(t) for t in prompt]
        finish = "length"
        owner = None
        owner_version = None
        hops = 0
        migrations = 0
        replicas_used = []
        failures = 0          # deaths + busy-rejections, bounded
        stalls = 0            # consecutive zero-token hops
        ttft_ms = None
        max_failures = max(2, self.retry_limit) * 4
        spec_w = 0            # token-weighted speculation aggregation
        spec_atps = 0.0
        spec_rate = 0.0

        def _note_spec(out, got):
            nonlocal spec_w, spec_atps, spec_rate
            atps = out.get("accepted_tokens_per_step")
            if atps is not None and got:
                spec_w += len(got)
                spec_atps += float(atps) * len(got)
                spec_rate += float(out.get("draft_acceptance_rate")
                                   or 0.0) * len(got)

        def _partial(status, err, retry_after=0.1):
            self._c_requests.inc(kind="generate", outcome="partial")
            return status, {
                "error": err, "tokens": tokens,
                "cursor": self._partial_cursor(prompt, tokens, remaining),
                "retry_after_s": retry_after,
            }, {"Retry-After": "%.3f" % retry_after}

        last_oid = None       # survives owner=None across a death
        while remaining > 0:
            if owner is None or not self.registry.is_routable(owner.id):
                try:
                    owner = self._pick(model, version, "generate",
                                       exclude=())
                except NoReplica as e:
                    return _partial(429, str(e), retry_after=1.0)
                owner_version = owner.version
                if last_oid is not None and owner.id != last_oid:
                    migrations += 1
                    self._c_migrations.inc()
                last_oid = owner.id
                if owner.id not in replicas_used:
                    replicas_used.append(owner.id)
            if deadline is not None and time.monotonic() >= deadline:
                return _partial(429, "fleet: request deadline reached "
                                     "mid-generation")
            n = min(remaining, hop) if hop > 0 else remaining
            ctx = int(owner.spec.get("max_context") or 0)
            if ctx and len(cur_prompt) + remaining > ctx:
                # definitive, not retryable: prompt + budget exceeds the
                # paged-cache geometry on every replica of this artifact
                # (len(cur_prompt) + remaining is invariant across hops
                # and eviction cursors, so this fires on the first hop)
                self._c_requests.inc(kind="generate", outcome="error")
                return 400, {
                    "error": "fleet: prompt %d + max_new_tokens %d "
                             "exceeds the artifact's max_context %d"
                             % (len(prompt),
                                int(payload.get("max_new_tokens") or 64),
                                ctx)}, {}
            cap = int(owner.spec.get("max_prompt_len") or 0)
            if (n < remaining and cap and len(cur_prompt) + n > cap
                    and not owner.spec.get("chunked_prefill")):
                # a resume point is prompt+generated, and it must fit
                # the artifact's prefill window to be resubmittable (the
                # same bound gates PR-9 eviction cursors). Once the
                # post-hop prompt would exceed max_prompt_len there is
                # nothing to migrate to, so stop chunking and forward
                # the whole remaining budget in one final hop. Replicas
                # that register chunked_prefill stream long resume
                # prompts through fixed-shape chunks up to max_context,
                # so for them the hop cap stays lifted and long decodes
                # remain migratable end to end.
                n = remaining
            body = {"prompt": cur_prompt, "max_new_tokens": int(n),
                    "temperature": temperature, "seed": seed}
            timeout_s = self.proxy_timeout_s
            if deadline is not None:
                budget_ms = max(1.0, (deadline - time.monotonic()) * 1e3)
                body["timeout_ms"] = budget_ms
                timeout_s = budget_ms / 1e3 + 30.0
            oid = owner.id
            self.registry.note_inflight(oid, +1)
            try:
                status, out, _headers = self._call(
                    owner.url + "/v1/generate", body, timeout_s)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # the owner died mid-hop; the hop's tokens died with its
                # KV pages — resubmitting cur_prompt on a survivor
                # regenerates them bitwise (position-keyed sampling)
                self.registry.mark_dead(
                    oid, "generate proxy failed: %s" % e)
                self._c_deaths.inc()
                failures += 1
                if failures > max_failures:
                    return _partial(429, "fleet: replica kept failing "
                                         "mid-generation")
                owner = None
                continue
            finally:
                self.registry.note_inflight(oid, -1)
            hops += 1
            self._c_hops.inc()
            if status == 200:
                got = [int(t) for t in out.get("tokens", [])]
                tokens.extend(got)
                remaining -= len(got)
                cur_prompt = cur_prompt + got
                if ttft_ms is None:
                    ttft_ms = out.get("ttft_ms")
                _note_spec(out, got)
                stalls = stalls + 1 if not got else 0
                if out.get("finish_reason") == "stop":
                    finish = "stop"
                    break
                if stalls >= 3:
                    return _partial(429, "fleet: generation stalled "
                                         "(3 empty hops)")
                continue
            if status == 429 and out.get("cursor"):
                # eviction (drain/deadline on the replica): bank the
                # partial tokens, resume from the cursor elsewhere
                got = [int(t) for t in out.get("tokens", [])]
                tokens.extend(got)
                remaining -= len(got)
                cur_prompt = [int(t) for t in out["cursor"]["resume_prompt"]]
                _note_spec(out, got)
                stalls = stalls + 1 if not got else 0
                if stalls >= 3:
                    return _partial(429, "fleet: generation stalled "
                                         "(3 empty eviction hops)")
                time.sleep(min(float(out.get("retry_after_s", 0.05)), 0.5))
                continue
            if status in (429, 503):       # busy or draining, no progress
                if status == 503:
                    self.registry.mark_not_ready(owner.id, "answered 503")
                    owner = None
                failures += 1
                if failures > max_failures:
                    return _partial(status, out.get(
                        "error", "fleet: replicas kept rejecting"))
                time.sleep(min(float((out or {}).get("retry_after_s",
                                                     0.05)), 0.5))
                continue
            # 400/500/504: definitive — propagate the replica's answer
            self._c_requests.inc(kind="generate", outcome="error")
            return status, out, {}
        self._c_requests.inc(kind="generate", outcome="ok")
        lat_ms = (time.monotonic() - t0) * 1e3
        n_gen = len(tokens)
        out = {
            "tokens": tokens,
            "finish_reason": finish,
            "ttft_ms": ttft_ms,
            "tpot_ms": (round((lat_ms - (ttft_ms or 0.0))
                              / max(1, n_gen - 1), 3)
                        if n_gen > 1 else None),
            "latency_ms": round(lat_ms, 3),
            "hops": hops,
            "migrations": migrations,
            "replicas": replicas_used,
            "replica": replicas_used[-1] if replicas_used else None,
            "version": owner_version,
        }
        if spec_w:
            out["accepted_tokens_per_step"] = round(spec_atps / spec_w, 4)
            out["draft_acceptance_rate"] = round(spec_rate / spec_w, 4)
        return 200, out, {}

    # -- blue/green + canary ------------------------------------------------
    def set_split(self, model, weights):
        """Set the version traffic split for ``model`` (weights are
        normalized; a missing version gets zero traffic)."""
        clean = {}
        for v, w in dict(weights).items():
            w = float(w)
            if w < 0:
                raise MXNetError("fleet: negative split weight %r for "
                                 "version %r" % (w, v))
            clean[str(v)] = w
        total = sum(clean.values())
        if total <= 0:
            raise MXNetError("fleet: split weights must sum > 0")
        with self._lock:
            self.splits[str(model)] = {v: w / total
                                       for v, w in clean.items()}
        return dict(self.splits[str(model)])

    def clear_split(self, model):
        with self._lock:
            self.splits.pop(str(model), None)

    def promote(self, model, version):
        """Blue/green flip: 100% of ``model`` traffic to ``version``.
        Old-version replicas stay registered (instant rollback path);
        their in-flight requests finish — the router just stops handing
        them new ones."""
        model, version = str(model), str(version)
        with self._lock:
            self.splits[model] = {version: 1.0}
            c = self.canaries.get(model)
            if c is not None and c["version"] == version:
                c["state"] = "promoted"
        return {"model": model, "split": {version: 1.0}}

    def start_canary(self, model, version, split=0.1, budget=None):
        """Send ``split`` of ``model`` traffic to ``version``; keep the
        previous split as the rollback baseline. ``budget`` defaults to
        the int8 accuracy budget flag — the PR-10 probe's top-1 delta
        is the rollback signal."""
        model, version = str(model), str(version)
        split = float(split)
        if not 0.0 < split < 1.0:
            raise MXNetError("fleet: canary split must be in (0, 1)")
        if budget is None:
            budget = flags.quant_accuracy_budget
        with self._lock:
            baseline = dict(self.splits.get(model) or {})
            if not baseline:
                versions = sorted(v for v in
                                  self.registry.models().get(model, {})
                                  if v != version)
                if not versions:
                    raise MXNetError(
                        "fleet: no baseline version of %r to canary "
                        "against" % model)
                baseline = {v: 1.0 / len(versions) for v in versions}
            mixed = {v: w * (1.0 - split) for v, w in baseline.items()}
            mixed[version] = mixed.get(version, 0.0) + split
            self.splits[model] = mixed
            self.canaries[model] = {
                "model": model, "version": version, "split": split,
                "budget": float(budget), "baseline": baseline,
                "deltas": [], "state": "active", "reason": None,
            }
            return dict(self.canaries[model], deltas=[])

    def report_canary(self, model, delta, version=None):
        """Feed one accuracy-probe delta (f32-vs-canary top-1 delta,
        ``tools/serve_loadgen.py --accuracy-probe`` shape). Exceeding
        the budget triggers automatic rollback: the canary version's
        weight goes to ZERO (baseline split restored) and its replicas
        are put in router-side draining — new traffic stops instantly,
        in-flight requests complete on the still-running processes, so
        nothing drops."""
        model = str(model)
        with self._lock:
            c = self.canaries.get(model)
            if c is None or c["state"] != "active":
                raise MXNetError(
                    "fleet: no active canary for model %r" % model)
            if version is not None and str(version) != c["version"]:
                raise MXNetError(
                    "fleet: canary for %r is version %r, not %r"
                    % (model, c["version"], version))
            delta = float(delta)
            c["deltas"].append(delta)
            if abs(delta) <= c["budget"]:
                return {"state": "active", "action": "none",
                        "delta": delta, "budget": c["budget"]}
            # rollback: restore the baseline split; the canary version
            # keeps weight 0 via absence from the split
            c["state"] = "rolled_back"
            reason = ("accuracy delta %.6f exceeds budget %.6f"
                      % (delta, c["budget"]))
            c["reason"] = reason
            self.splits[model] = {v: w for v, w in c["baseline"].items()
                                  if v != c["version"]} or c["baseline"]
            canary_version = c["version"]
            budget = c["budget"]
        self._c_rollbacks.inc()
        drained = []
        for rep in self.registry.live_replicas():
            if rep.model == model and rep.version == canary_version:
                self.registry.set_draining(rep.id)
                drained.append(rep.id)
        return {"state": "rolled_back", "action": "rollback",
                "delta": delta, "budget": budget, "reason": reason,
                "drained_replicas": drained}

    # -- observability ------------------------------------------------------
    def federated_metrics(self):
        """The fleet ``/metrics`` body: every live replica's exposition
        merged under ``replica=<id>`` labels, plus the router's own
        series as ``replica="router"``."""
        sources = [("router", telemetry.prometheus_text())]
        errors = {}
        for rep in self.registry.live_replicas():
            try:
                sources.append((rep.id, self._scrape(rep.url)))
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError) as e:
                errors[rep.id] = str(e)
        text, skipped = federate.merge_expositions(sources)
        for sid, err in skipped:
            errors[sid] = "unparseable exposition: %s" % err
        return text, errors

    def fleet_snapshot(self):
        self.registry.sweep()
        with self._lock:
            splits = {m: dict(s) for m, s in self.splits.items()}
            canaries = {m: {k: v for k, v in c.items() if k != "deltas"}
                        for m, c in self.canaries.items()}
        snap = self.registry.snapshot()
        snap["splits"] = splits
        snap["canaries"] = canaries
        snap["models"] = self.registry.models()
        return snap


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n).decode() or "{}")

    def do_GET(self):
        router = self.server.mx_router
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            accept = self.headers.get("Accept", "")
            wants_prom = ("format=prometheus" in query
                          or ("text/plain" in accept
                              and "application/json" not in accept))
            if wants_prom:
                text, errors = router.federated_metrics()
                if errors:
                    text += "# fleet: %d replica scrapes failed\n" \
                        % len(errors)
                data = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 telemetry.prom.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(200, router.fleet_snapshot())
        elif path == "/fleet":
            self._reply(200, router.fleet_snapshot())
        elif path == "/healthz":
            snap = router.registry.snapshot()
            ok = snap["counts"]["ready"] > 0
            self._reply(200 if ok else 503,
                        {"status": "ok" if ok else "no_ready_replicas",
                         "replicas": snap["counts"]})
        elif path == "/readyz":
            snap = router.registry.snapshot()
            ok = snap["counts"]["ready"] > 0
            self._reply(200 if ok else 503,
                        {"ready": ok, "replicas": snap["counts"]})
        elif path == "/livez":
            self._reply(200, {"alive": True})
        else:
            self._reply(404, {"error": "no such endpoint %r" % self.path})

    def do_POST(self):
        router = self.server.mx_router
        try:
            payload = self._read_json()
        except ValueError as e:
            self._reply(400, {"error": "bad json: %s" % e})
            return
        try:
            if self.path in ("/v1/predict", "/predict"):
                code, out, headers = router.route_predict(payload)
                self._reply(code, out, headers)
            elif self.path in ("/v1/generate", "/generate"):
                code, out, headers = router.route_generate(payload)
                self._reply(code, out, headers)
            elif self.path == "/fleet/register":
                rep = router.registry.register(payload)
                self._reply(200, {"registered": rep.id})
            elif self.path == "/fleet/heartbeat":
                known = router.registry.heartbeat(
                    payload.get("id"), ready=payload.get("ready"),
                    reason=payload.get("reason"),
                    load=payload.get("load"))
                self._reply(200, {"known": known})
            elif self.path == "/fleet/deregister":
                router.registry.deregister(payload.get("id"))
                self._reply(200, {"deregistered": True})
            elif self.path == "/admin/split":
                split = router.set_split(payload["model"],
                                         payload["weights"])
                self._reply(200, {"model": payload["model"],
                                  "split": split})
            elif self.path == "/admin/promote":
                self._reply(200, router.promote(payload["model"],
                                                payload["version"]))
            elif self.path == "/admin/canary":
                self._reply(200, router.start_canary(
                    payload["model"], payload["version"],
                    split=payload.get("split", 0.1),
                    budget=payload.get("budget")))
            elif self.path == "/admin/canary/report":
                self._reply(200, router.report_canary(
                    payload["model"], payload["delta"],
                    version=payload.get("version")))
            elif self.path == "/admin/drain":
                ok = router.registry.set_draining(
                    payload["id"], payload.get("draining", True))
                self._reply(200 if ok else 404,
                            {"id": payload["id"], "draining": ok})
            else:
                self._reply(404, {"error": "no such endpoint %r"
                                           % self.path})
        except (MXNetError, KeyError, TypeError, ValueError) as e:
            self._reply(400, {"error": str(e)})


class RouterHTTPFrontEnd:
    """Owns the router's ThreadingHTTPServer + accept thread (the same
    shape as serve/http.HttpFrontEnd, so tools share idiom)."""

    def __init__(self, router, host="127.0.0.1", port=8090, verbose=False):
        self.mx_router = router
        self.httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self.httpd.mx_router = router
        self.httpd.verbose = verbose
        self.httpd.daemon_threads = True
        self._thread = None

    @property
    def address(self):
        h, p = self.httpd.server_address[:2]
        return "http://%s:%d" % (h, p)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="mxtpu-fleet-http",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)


def route_http(router, host="127.0.0.1", port=8090, verbose=False):
    """Start the fleet HTTP front end; returns the running
    :class:`RouterHTTPFrontEnd` (``.stop()`` to shut down)."""
    return RouterHTTPFrontEnd(router, host, port, verbose=verbose).start()
