"""Fleet router: the tier above one ``serve/`` process.

One router process fronts N replica servers (``tools/serve.py
--register``) and speaks the *same* client protocol they do, so a
client pointed at a replica yesterday points at the router today:

* ``POST /v1/predict`` — **least-loaded**: each replica's heartbeat
  carries ``load_s`` (estimated seconds of queued work) and ``unit_s``
  (estimated seconds per marginal request), both derived from
  ``perfmodel.roofline_seconds`` on the replica (the identical cost
  tables its own admission control uses); the router picks the minimum
  ``load_s + inflight * unit_s`` and retries rejections/deaths on the
  next-best replica.
* ``POST /v1/generate`` — **session-affine with cursor migration**: a
  decode session's KV pages live on one replica, so the router parks
  the whole generation there — but forwards it in *hops* of at most
  ``MXNET_FLEET_HOP_TOKENS`` tokens, which means it always holds a
  resume point (``prompt + tokens so far``, the exact shape of the
  PR-9 eviction cursor). When the owner dies mid-hop or drains
  (eviction cursor in a 429), the router resubmits on a survivor and
  stitches the tail; position-keyed sampling makes the stitched stream
  **bitwise identical** to an uninterrupted run, which the migration
  test asserts token-for-token.
* blue/green + canary: replicas register under ``(model, version)``;
  ``/admin/split`` sets version weights, ``/admin/canary`` starts a
  canary at a small split with the PR-10 accuracy-probe delta as the
  rollback signal (``/admin/canary/report``; budget
  ``MXNET_QUANT_ACCURACY_BUDGET``), and rollback is router-side only —
  new traffic stops, in-flight requests on the canary finish — so zero
  requests drop.
* ``GET /metrics`` — federation: every live replica's exposition
  merged under ``replica="<id>"`` labels plus the router's own
  ``fleet/*`` series (``telemetry/federate.py``).
* **HA** (``fleet/journal.py``): with a journal attached, every
  registry mutation and per-session hop cursor is write-ahead logged;
  ``Router.from_journal`` rebuilds a crashed primary's state — a warm
  standby (``tools/route.py --standby``) or supervised restart adopts
  the orphaned generate sessions at their last cursor and finishes
  them bitwise. Fencing epochs (``fleet/fencing.py``) ride every
  forwarded body and control-plane reply so a revived stale primary
  cannot split-brain the fleet.

Import-light by design (stdlib + config + telemetry): the router never
runs model code or touches a device — replicas own the accelerators;
the router holds only cursors, counters, and the registry.
"""
from __future__ import annotations

import hashlib
import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..base import MXNetError
from ..config import flags
from .. import telemetry
from ..telemetry import federate
from .registry import ReplicaRegistry

__all__ = ["Router", "NoReplica", "JournalDegraded", "RouterHTTPFrontEnd",
           "route_http"]


class NoReplica(MXNetError):
    """No ready replica can take this request."""


class JournalDegraded(MXNetError):
    """The fleet journal is unwritable (disk full, dying, gone):
    control-plane mutations cannot be made durable, so acknowledging
    one could silently lose it on the next failover. The HTTP front
    end maps this to 503 + ``Retry-After``; already-routed data-plane
    traffic is unaffected."""

    retry_after_s = 1.0


class Router:
    """Routing core; :class:`RouterHTTPFrontEnd` is the wire skin.

    Public entry points (``route_predict``/``route_generate``) return
    ``(status_code, payload_dict, extra_headers)`` so the HTTP handler
    and in-process tests share one code path."""

    def __init__(self, registry=None, hop_tokens=None, retry_limit=None,
                 proxy_timeout_s=None, rng=None, journal=None,
                 epoch=None):
        self.registry = registry or ReplicaRegistry()
        self.hop_tokens = (flags.fleet_hop_tokens if hop_tokens is None
                           else int(hop_tokens))
        self.retry_limit = (flags.fleet_retry_limit if retry_limit is None
                            else int(retry_limit))
        self.proxy_timeout_s = (flags.fleet_proxy_timeout_s
                                if proxy_timeout_s is None
                                else float(proxy_timeout_s))
        self._rng = rng or random.Random(0x5EED)
        self._lock = threading.Lock()
        self.splits = {}     # model -> {version: weight} (normalized)
        self.canaries = {}   # model -> canary record dict
        self.journal = None  # FleetJournal once attach_journal() wires it
        self.epoch = None if epoch is None else int(epoch)
        self.address = None  # bound URL, once announce() learns it
        self.replay_stats = None
        self._sessions = {}  # sid -> journal-backed generate hop cursor
        # scaler key -> {owned, last}: the autoscaler's durable view,
        # journaled per decision so a promoted standby inherits which
        # replicas were autoscaler-launched (Autoscaler.restore reads it)
        self.autoscale_state = {}
        self.journal_degraded = False   # journal unwritable (ENOSPC...)
        self.degraded_reason = None
        reg = telemetry.default_registry()
        self._c_requests = reg.counter(
            "fleet/requests", "Requests routed, by kind and outcome.")
        self._c_retries = reg.counter(
            "fleet/retries", "Re-routes after a replica rejected/died.")
        self._c_hops = reg.counter(
            "fleet/generate_hops", "Generate hops forwarded to replicas.")
        self._c_migrations = reg.counter(
            "fleet/migrations",
            "Decode sessions moved to a surviving replica via cursor.")
        self._c_deaths = reg.counter(
            "fleet/replica_deaths", "Replicas marked dead by the router.")
        self._c_rollbacks = reg.counter(
            "fleet/canary_rollbacks", "Canaries auto-rolled back.")
        self._g_ready = reg.gauge(
            "fleet/replicas_ready", "Replicas currently in rotation.")
        self._c_failover = reg.counter(
            "fleet/failover_count", "Router incarnations that took over "
            "a non-empty fleet journal (standby promotion or supervised "
            "restart replay).")
        self._c_resumed = reg.counter(
            "fleet/failover_resumed_sessions", "Orphaned generate "
            "sessions adopted from journaled hop cursors after a "
            "router failover.")
        self._g_replay = reg.gauge(
            "fleet/replay_ms", "Duration of the last fleet journal "
            "replay (ms).")
        self._g_epoch = reg.gauge(
            "fleet/epoch", "This router's fencing epoch.")
        self._g_degraded = reg.gauge(
            "fleet/journal_degraded", "1 while the fleet journal is "
            "unwritable: control-plane mutations are refused with 503, "
            "data-plane routing continues.")
        if journal is not None:
            self.attach_journal(journal)

    # -- HA: journal + fencing epochs ---------------------------------------
    def attach_journal(self, journal):
        """Make this router the journal's primary: registry mutations
        and session cursors flow into it from now on. Assigns epoch 1
        for a fresh journal; :meth:`from_journal` passes replayed-max+1
        via the constructor before calling this."""
        self.journal = journal
        if self.epoch is None:
            self.epoch = 1
        self._g_epoch.set(self.epoch)
        self.registry.on_mutation = self._journal_append

    def _journal_append(self, kind, data, sync=False, required=False):
        if self.journal is None:
            return
        # registrations, epoch claims, and acked control mutations are
        # rare and structural: always durable. Hop cursors ride the
        # group commit.
        sync = sync or kind in ("register", "deregister", "epoch",
                                "split", "canary")
        try:
            self.journal.append(kind, data, sync=sync)
        except OSError as e:
            # the journal is unwritable: degrade the control plane but
            # keep routing — already-adopted sessions continue on their
            # in-memory cursors, and losing durability only costs a
            # resumed session some bitwise-regenerated tokens.
            # ``required`` marks an acked-iff-durable control mutation:
            # those refuse (503) instead of acking a record that would
            # silently vanish on the next failover.
            self._enter_degraded(e)
            if required:
                raise JournalDegraded(
                    "fleet: journal unwritable (%s) — control-plane "
                    "mutation not acknowledged; retry after the disk "
                    "recovers" % e)

    # -- HA: storage degradation (journal unwritable) -----------------------
    def _enter_degraded(self, err):
        first = not self.journal_degraded
        self.journal_degraded = True
        self.degraded_reason = str(err)
        if first:
            self._g_degraded.set(1)
            telemetry.flight_recorder().record_event(
                "journal_degraded", error=str(err))

    def _exit_degraded(self):
        if self.journal_degraded:
            self.journal_degraded = False
            self.degraded_reason = None
            self._g_degraded.set(0)
            telemetry.flight_recorder().record_event("journal_recovered")

    def check_journal(self):
        """Probe the journal with a *synced* no-op append; on success
        exit degraded mode in place (no restart) and compact so every
        mutation the journal missed while unwritable is recaptured in
        the snapshot. Returns True when the journal is writable."""
        if self.journal is None or not self.journal_degraded:
            return True
        try:
            self.journal.append("noop", {"probe": True}, sync=True)
            self.journal.compact(self.export_state())
        except OSError as e:
            self.degraded_reason = str(e)
            return False
        self._exit_degraded()
        return True

    def _require_journal_writable(self):
        """Gate for control-plane mutations: while the journal is
        unwritable they cannot be made durable, so acknowledging them
        could lose them on failover — refuse with 503 + Retry-After
        instead. Probes first, so a recovered disk exits degraded mode
        on the next control attempt, no restart needed."""
        if self.journal is not None and self.journal_degraded \
                and not self.check_journal():
            raise JournalDegraded(
                "fleet: journal unwritable (%s) — control plane is "
                "read-only until the disk recovers"
                % self.degraded_reason)

    # -- HA: journal replication (primary side) -----------------------------
    def journal_manifest(self):
        """The replication manifest a pulling standby polls; None when
        no journal is attached."""
        if self.journal is None:
            return None
        from .replicate import build_manifest
        man = build_manifest(self.journal.dir, self.epoch,
                             self.journal.seq)
        man["degraded"] = self.journal_degraded
        return man

    def journal_read(self, name, offset=0):
        """Bounded byte-range read of one journal file for a
        replication fetch. Raises ``KeyError`` for anything that is
        not a journal file of ours."""
        if self.journal is None:
            raise KeyError("no journal attached")
        from .replicate import read_journal_file
        return read_journal_file(self.journal.dir, name, offset)

    def announce(self, address):
        """Journal this incarnation's epoch claim + bound address (the
        record a standby reads to know where to take over)."""
        self.address = str(address)
        if self.epoch is not None:
            self._journal_append(
                "epoch", {"epoch": self.epoch, "address": self.address})

    @classmethod
    def from_journal(cls, journal_dir, registry=None, sync_every=None,
                     **kw):
        """Build a router by replaying ``journal_dir``: restores the
        replica table, splits, canaries, and every in-flight generate
        session (as adoptable orphans), claims epoch replayed-max+1,
        and starts appending to a fresh segment. This is both the
        standby-promotion and the supervised-restart path."""
        from . import journal as journal_mod
        t0 = time.monotonic()
        state, stats = journal_mod.replay(journal_dir)
        router = cls(registry=registry, epoch=state.epoch + 1, **kw)
        router._restore_state(state)
        router.attach_journal(journal_mod.FleetJournal(
            journal_dir, start_seq=state.applied_seq,
            sync_every=sync_every))
        # make the epoch claim durable NOW (fsynced): a revived stale
        # primary replaying later must see it and stand down. announce()
        # re-records it with the freshly bound address; until then the
        # predecessor's address is inherited for tailing standbys.
        router.address = state.address
        router._journal_append("epoch", {"epoch": router.epoch,
                                         "address": router.address})
        replay_ms = round((time.monotonic() - t0) * 1e3, 3)
        router._g_replay.set(replay_ms)
        if state.applied_seq > 0:
            router._c_failover.inc()
        router.replay_stats = dict(
            stats, replay_ms=replay_ms, epoch=router.epoch,
            replicas=len(state.replicas),
            resumed_sessions=len(state.sessions))
        return router

    def _restore_state(self, state):
        self.registry.restore(state.replicas.values())
        with self._lock:
            self.splits = {m: dict(w) for m, w in state.splits.items()}
            self.canaries = {m: dict(c)
                             for m, c in state.canaries.items()}
            # orphan=True: adoptable by the retried client request with
            # the matching session id — never double-run concurrently
            self._sessions = {sid: dict(s, orphan=True)
                              for sid, s in state.sessions.items()}
            self.autoscale_state = {k: dict(v)
                                    for k, v in state.autoscale.items()}

    def export_state(self):
        """The current control-plane state as a :class:`FleetState`
        (what SIGTERM compaction snapshots)."""
        from .journal import FleetState
        st = FleetState()
        st.epoch = self.epoch or 0
        st.address = self.address
        if self.journal is not None:
            st.applied_seq = self.journal.seq
        st.replicas = {r.id: r.to_info()
                       for r in self.registry.replicas()}
        with self._lock:
            st.splits = {m: dict(w) for m, w in self.splits.items()}
            st.canaries = {m: dict(c) for m, c in self.canaries.items()}
            st.sessions = {sid: {k: v for k, v in s.items()
                                 if k != "orphan"}
                           for sid, s in self._sessions.items()}
            st.autoscale = {k: dict(v)
                            for k, v in self.autoscale_state.items()}
        return st

    def record_autoscale(self, data, sync=True):
        """Journal one autoscaling decision and fold it into the
        in-memory scaler state with the same reducer
        ``FleetState.apply`` uses — ``export_state()`` and
        ``fleet_snapshot()`` reflect the decision immediately, and a
        promoted standby replays it."""
        data = dict(data)
        key = str(data.get("scaler") or "default")
        self._journal_append("autoscale", data, sync=sync)
        with self._lock:
            rec = self.autoscale_state.setdefault(key, {})
            if "owned" in data:
                rec["owned"] = list(data["owned"] or [])
            rec["last"] = {k: v for k, v in data.items()
                           if k not in ("scaler", "owned")}

    def _stamp_epoch(self, body):
        if self.epoch is not None:
            body["fleet_epoch"] = self.epoch
        return body

    # -- HA: durable generate-session cursors -------------------------------
    @staticmethod
    def _session_id(payload):
        """Stable id for one logical generation. Explicit
        ``session_id`` wins; otherwise the request parameters hash —
        so the *identical* retried request a client sends when the
        primary died before answering maps onto the journaled orphan."""
        sid = payload.get("session_id")
        if sid:
            return str(sid)
        key = json.dumps(
            [payload.get("model"), payload.get("version"),
             [int(t) for t in payload.get("prompt") or []],
             int(payload.get("max_new_tokens") or 64),
             payload.get("temperature", 0.0), payload.get("seed", 0)],
            sort_keys=True)
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def _has_orphan(self, sid):
        with self._lock:
            s = self._sessions.get(sid)
            return s is not None and bool(s.get("orphan"))

    def _adopt_session(self, sid):
        """Claim a journal-replayed orphan for this request thread;
        returns its cursor dict or None."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None and s.get("orphan"):
                s = dict(s, orphan=False)
                self._sessions[sid] = s
                return s
        return None

    def _checkpoint_session(self, sid, payload, tokens, cur_prompt,
                            remaining):
        """After every hop that made progress: the newest resume point,
        in memory and in the journal (group-committed — losing the
        unsynced tail only means resuming from an older cursor, which
        position-keyed sampling regenerates bitwise)."""
        if self.journal is None:
            return
        rec = {"sid": sid,
               "model": payload.get("model"),
               "prompt": [int(t) for t in payload.get("prompt") or []],
               "tokens": list(tokens),
               "resume_prompt": list(cur_prompt),
               "remaining": int(remaining),
               "max_new_tokens": int(payload.get("max_new_tokens")
                                     or 64),
               "temperature": payload.get("temperature", 0.0),
               "seed": payload.get("seed", 0)}
        with self._lock:
            self._sessions[sid] = dict(rec, orphan=False)
        self._journal_append("session", rec)

    def _finish_session(self, sid):
        """The client got a definitive answer (final tokens or a
        partial WITH its cursor): the router's durable copy is done."""
        if self.journal is None:
            return
        with self._lock:
            known = self._sessions.pop(sid, None) is not None
        if known:
            self._journal_append("session_done", {"sid": sid})

    # -- proxy plumbing -----------------------------------------------------
    def _call(self, url, payload, timeout_s):
        """POST json; returns (status, body_dict, headers). Connection
        failures raise (the caller marks the replica dead)."""
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, json.loads(r.read().decode() or "{}"), \
                    dict(r.headers)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode() or "{}")
            except ValueError:
                body = {"error": "replica returned unparseable body"}
            return e.code, body, dict(e.headers)

    def _scrape(self, url, timeout_s=5.0):
        req = urllib.request.Request(
            url + "/metrics?format=prometheus",
            headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.read().decode("utf-8")

    # -- replica selection --------------------------------------------------
    def _resolve_model(self, model, cands):
        if model is not None:
            return str(model)
        names = sorted({r.model for r in cands})
        if len(names) == 1:
            return names[0]
        raise NoReplica(
            "fleet: %d models hosted (%s); the request must name one "
            'with {"model": ...}' % (len(names), names))

    def _choose_version(self, model, by_version):
        """Weighted version choice per the model's traffic split; falls
        back to every ready version (availability beats policy) when
        the split names none of them."""
        with self._lock:
            split = dict(self.splits.get(model) or {})
        if split:
            avail = {v: w for v, w in split.items()
                     if v in by_version and w > 0.0}
            if avail:
                total = sum(avail.values())
                x = self._rng.random() * total
                for v, w in sorted(avail.items()):
                    x -= w
                    if x <= 0:
                        return v
                return sorted(avail)[-1]
            # a split is a statement of intent: versions weighted 0 (a
            # rolled-back canary) stay out even when the split's chosen
            # versions are all down — unless NOTHING else is ready.
            allowed = [v for v in by_version if v not in split]
            if allowed:
                return None if len(allowed) > 1 else allowed[0]
        return None    # no preference: least-loaded across all versions

    def _pick(self, model=None, version=None, mode=None, exclude=()):
        cands = self.registry.routable(model=model, mode=mode)
        cands = [r for r in cands if r.id not in exclude]
        self._g_ready.set(len(cands))
        if not cands:
            raise NoReplica(
                "fleet: no ready %s replica%s%s (check /fleet for "
                "replica states)"
                % (mode or "", " for model %r" % model if model else "",
                   " excluding %s" % sorted(exclude) if exclude else ""))
        model = self._resolve_model(model, cands)
        cands = [r for r in cands if r.model == model]
        if not cands:
            raise NoReplica("fleet: no ready replica for model %r" % model)
        if version is None:
            by_version = {}
            for r in cands:
                by_version.setdefault(r.version, []).append(r)
            chosen = self._choose_version(model, by_version)
            if chosen is not None:
                cands = by_version[chosen]
        else:
            cands = [r for r in cands if r.version == str(version)]
            if not cands:
                raise NoReplica(
                    "fleet: no ready replica for model %r version %r"
                    % (model, version))
        # least-loaded on the perfmodel-derived heartbeat score;
        # `served` tie-breaks into round-robin on a cold fleet
        return min(cands, key=lambda r: (r.score(), r.served, r.id))

    # -- simple proxy paths (predict, recommend) ----------------------------
    def _route_simple(self, payload, mode, path):
        """Single-shot proxy with least-loaded pick + failover retry:
        the shared shape of every request/response leg whose state
        lives entirely in one replica call (predict rows, recommend
        id-lists — unlike generate, which hop-chunks a cursor)."""
        model = payload.get("model")
        version = payload.get("version")
        body = {k: v for k, v in payload.items()
                if k not in ("model", "version")}
        self._stamp_epoch(body)
        timeout_s = self.proxy_timeout_s
        if payload.get("timeout_ms"):
            timeout_s = payload["timeout_ms"] / 1e3 + 5.0
        tried = set()
        last = None
        for attempt in range(self.retry_limit + 1):
            try:
                rep = self._pick(model, version, mode, exclude=tried)
            except NoReplica as e:
                if last is not None:
                    self._c_requests.inc(kind=mode, outcome="rejected")
                    return last
                self._c_requests.inc(kind=mode, outcome="no_replica")
                return 503, {"error": str(e)}, {}
            tried.add(rep.id)
            if attempt > 0:
                self._c_retries.inc(kind=mode)
            self.registry.note_inflight(rep.id, +1)
            try:
                status, out, headers = self._call(
                    rep.url + path, body, timeout_s)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                self.registry.mark_dead(
                    rep.id, "%s proxy failed: %s" % (mode, e))
                self._c_deaths.inc()
                continue
            finally:
                self.registry.note_inflight(rep.id, -1)
            if status == 200:
                out["replica"] = rep.id
                out["version"] = rep.version
                self._c_requests.inc(kind=mode, outcome="ok")
                return 200, out, {}
            if status in (429, 503):
                # busy/draining: remember the hint, try the next-best
                extra = {}
                if headers.get("Retry-After"):
                    extra["Retry-After"] = headers["Retry-After"]
                if status == 503:
                    self.registry.mark_not_ready(rep.id, "answered 503")
                last = (status, out, extra)
                continue
            # 400/500/504: the replica answered definitively
            self._c_requests.inc(kind=mode, outcome="error")
            return status, out, {}
        self._c_requests.inc(kind=mode, outcome="rejected")
        return last or (503, {"error": "fleet: every replica rejected "
                                       "this request"}, {})

    def route_predict(self, payload):
        return self._route_simple(payload, "predict", "/v1/predict")

    def route_recommend(self, payload):
        """Recommend requests are ragged and billed in gather units by
        the replica's admission queue; the router needs no new policy —
        least-loaded already scores the heartbeat ``load_s`` that
        recommend replicas derive from pending gathers x per-gather
        roofline."""
        return self._route_simple(payload, "recommend", "/v1/recommend")

    # -- generate path ------------------------------------------------------
    def _partial_cursor(self, prompt, tokens, remaining):
        # same shape GenerateSession._cursor emits, so a client can
        # resubmit a router-partial exactly like a replica eviction
        return {"prompt": list(prompt), "generated": list(tokens),
                "resume_prompt": list(prompt) + list(tokens),
                "remaining_tokens": int(remaining)}

    def route_generate(self, payload):
        model = payload.get("model")
        version = payload.get("version")
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return 400, {"error": 'body must be {"prompt": [ids], ...}'}, {}
        # mirror tools/serve.py's --max-new-tokens default: hop chunking
        # needs a concrete total budget
        remaining = int(payload.get("max_new_tokens") or 64)
        temperature = payload.get("temperature", 0.0)
        seed = payload.get("seed", 0)
        deadline = None
        if payload.get("timeout_ms"):
            deadline = time.monotonic() + payload["timeout_ms"] / 1e3
        hop = self.hop_tokens
        t0 = time.monotonic()
        tokens = []
        cur_prompt = [int(t) for t in prompt]
        sid = self._session_id(payload)
        if self._has_orphan(sid) and self.journal_degraded \
                and not self.check_journal():
            # adopting an orphan claims exclusive ownership, and that
            # claim's progress must be journalable before we run it —
            # after another failover an un-checkpointed adopted session
            # would replay from a stale cursor while the client already
            # holds newer tokens. Requests WITHOUT an orphan are plain
            # data plane and flow normally even while degraded.
            self._c_requests.inc(kind="generate", outcome="degraded")
            return 503, {"error": "fleet: journal degraded — session "
                                  "adoption paused until the disk "
                                  "recovers",
                         "retry_after_s": JournalDegraded.retry_after_s}, \
                {"Retry-After": "1"}
        adopted = self._adopt_session(sid)
        if adopted is not None:
            # this exact request was in flight when the previous router
            # incarnation died: resume from its journaled hop cursor
            # instead of re-running the prefix (either way the tokens
            # are bitwise-identical; this way they are cheaper)
            tokens = [int(t) for t in adopted.get("tokens") or []]
            if adopted.get("resume_prompt"):
                cur_prompt = [int(t) for t in adopted["resume_prompt"]]
            if adopted.get("remaining") is not None:
                remaining = int(adopted["remaining"])
            self._c_resumed.inc()
        finish = "length"
        owner = None
        owner_version = None
        hops = 0
        migrations = 0
        replicas_used = []
        failures = 0          # deaths + busy-rejections, bounded
        stalls = 0            # consecutive zero-token hops
        ttft_ms = None
        max_failures = max(2, self.retry_limit) * 4
        spec_w = 0            # token-weighted speculation aggregation
        spec_atps = 0.0
        spec_rate = 0.0

        def _note_spec(out, got):
            nonlocal spec_w, spec_atps, spec_rate
            atps = out.get("accepted_tokens_per_step")
            if atps is not None and got:
                spec_w += len(got)
                spec_atps += float(atps) * len(got)
                spec_rate += float(out.get("draft_acceptance_rate")
                                   or 0.0) * len(got)

        def _partial(status, err, retry_after=0.1):
            self._c_requests.inc(kind="generate", outcome="partial")
            # the client receives the cursor: durability hands over to
            # its resubmission, the journal copy would only shadow it
            self._finish_session(sid)
            return status, {
                "error": err, "tokens": tokens,
                "cursor": self._partial_cursor(prompt, tokens, remaining),
                "retry_after_s": retry_after,
            }, {"Retry-After": "%.3f" % retry_after}

        last_oid = None       # survives owner=None across a death
        while remaining > 0:
            if owner is None or not self.registry.is_routable(owner.id):
                try:
                    owner = self._pick(model, version, "generate",
                                       exclude=())
                except NoReplica as e:
                    return _partial(429, str(e), retry_after=1.0)
                owner_version = owner.version
                if last_oid is not None and owner.id != last_oid:
                    migrations += 1
                    self._c_migrations.inc()
                last_oid = owner.id
                if owner.id not in replicas_used:
                    replicas_used.append(owner.id)
            if deadline is not None and time.monotonic() >= deadline:
                return _partial(429, "fleet: request deadline reached "
                                     "mid-generation")
            n = min(remaining, hop) if hop > 0 else remaining
            ctx = int(owner.spec.get("max_context") or 0)
            if ctx and len(cur_prompt) + remaining > ctx:
                # definitive, not retryable: prompt + budget exceeds the
                # paged-cache geometry on every replica of this artifact
                # (len(cur_prompt) + remaining is invariant across hops
                # and eviction cursors, so this fires on the first hop)
                self._c_requests.inc(kind="generate", outcome="error")
                self._finish_session(sid)
                return 400, {
                    "error": "fleet: prompt %d + max_new_tokens %d "
                             "exceeds the artifact's max_context %d"
                             % (len(prompt),
                                int(payload.get("max_new_tokens") or 64),
                                ctx)}, {}
            cap = int(owner.spec.get("max_prompt_len") or 0)
            if (n < remaining and cap and len(cur_prompt) + n > cap
                    and not owner.spec.get("chunked_prefill")):
                # a resume point is prompt+generated, and it must fit
                # the artifact's prefill window to be resubmittable (the
                # same bound gates PR-9 eviction cursors). Once the
                # post-hop prompt would exceed max_prompt_len there is
                # nothing to migrate to, so stop chunking and forward
                # the whole remaining budget in one final hop. Replicas
                # that register chunked_prefill stream long resume
                # prompts through fixed-shape chunks up to max_context,
                # so for them the hop cap stays lifted and long decodes
                # remain migratable end to end.
                n = remaining
            body = {"prompt": cur_prompt, "max_new_tokens": int(n),
                    "temperature": temperature, "seed": seed}
            self._stamp_epoch(body)
            timeout_s = self.proxy_timeout_s
            if deadline is not None:
                budget_ms = max(1.0, (deadline - time.monotonic()) * 1e3)
                body["timeout_ms"] = budget_ms
                timeout_s = budget_ms / 1e3 + 30.0
            oid = owner.id
            self.registry.note_inflight(oid, +1)
            try:
                status, out, _headers = self._call(
                    owner.url + "/v1/generate", body, timeout_s)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # the owner died mid-hop; the hop's tokens died with its
                # KV pages — resubmitting cur_prompt on a survivor
                # regenerates them bitwise (position-keyed sampling)
                self.registry.mark_dead(
                    oid, "generate proxy failed: %s" % e)
                self._c_deaths.inc()
                failures += 1
                if failures > max_failures:
                    return _partial(429, "fleet: replica kept failing "
                                         "mid-generation")
                owner = None
                continue
            finally:
                self.registry.note_inflight(oid, -1)
            hops += 1
            self._c_hops.inc()
            if status == 200:
                got = [int(t) for t in out.get("tokens", [])]
                tokens.extend(got)
                remaining -= len(got)
                cur_prompt = cur_prompt + got
                if ttft_ms is None:
                    ttft_ms = out.get("ttft_ms")
                _note_spec(out, got)
                stalls = stalls + 1 if not got else 0
                if out.get("finish_reason") == "stop":
                    finish = "stop"
                    break
                if got:
                    self._checkpoint_session(sid, payload, tokens,
                                             cur_prompt, remaining)
                if stalls >= 3:
                    return _partial(429, "fleet: generation stalled "
                                         "(3 empty hops)")
                continue
            if status == 429 and out.get("cursor"):
                # eviction (drain/deadline on the replica): bank the
                # partial tokens, resume from the cursor elsewhere
                got = [int(t) for t in out.get("tokens", [])]
                tokens.extend(got)
                remaining -= len(got)
                cur_prompt = [int(t) for t in out["cursor"]["resume_prompt"]]
                if got:
                    self._checkpoint_session(sid, payload, tokens,
                                             cur_prompt, remaining)
                _note_spec(out, got)
                stalls = stalls + 1 if not got else 0
                if stalls >= 3:
                    return _partial(429, "fleet: generation stalled "
                                         "(3 empty eviction hops)")
                time.sleep(min(float(out.get("retry_after_s", 0.05)), 0.5))
                continue
            if status in (429, 503):       # busy or draining, no progress
                if status == 503:
                    self.registry.mark_not_ready(owner.id, "answered 503")
                    owner = None
                failures += 1
                if failures > max_failures:
                    return _partial(status, out.get(
                        "error", "fleet: replicas kept rejecting"))
                time.sleep(min(float((out or {}).get("retry_after_s",
                                                     0.05)), 0.5))
                continue
            # 400/500/504: definitive — propagate the replica's answer
            self._c_requests.inc(kind="generate", outcome="error")
            self._finish_session(sid)
            return status, out, {}
        self._c_requests.inc(kind="generate", outcome="ok")
        self._finish_session(sid)
        lat_ms = (time.monotonic() - t0) * 1e3
        n_gen = len(tokens)
        out = {
            "tokens": tokens,
            "finish_reason": finish,
            "ttft_ms": ttft_ms,
            "tpot_ms": (round((lat_ms - (ttft_ms or 0.0))
                              / max(1, n_gen - 1), 3)
                        if n_gen > 1 else None),
            "latency_ms": round(lat_ms, 3),
            "hops": hops,
            "migrations": migrations,
            "replicas": replicas_used,
            "replica": replicas_used[-1] if replicas_used else None,
            "version": owner_version,
        }
        if spec_w:
            out["accepted_tokens_per_step"] = round(spec_atps / spec_w, 4)
            out["draft_acceptance_rate"] = round(spec_rate / spec_w, 4)
        return 200, out, {}

    # -- blue/green + canary ------------------------------------------------
    def _refuse_mixed_layouts(self, model, versions):
        """A hop cursor is only portable between replicas that agree
        on the parameter layout (cache geometry bakes into the decode
        shapes), so a split mixing layout fingerprints would strand
        migrating sessions mid-generation — refuse it. Replicas that
        registered no layout (predict artifacts, older serves) are
        exempt: only *disagreeing known* fingerprints refuse."""
        fps = {}
        for rep in self.registry.live_replicas():
            if rep.model != model or str(rep.version) not in versions:
                continue
            lay = getattr(rep, "layout", None)
            fp = lay.get("fingerprint") if isinstance(lay, dict) else None
            if fp:
                fps.setdefault(str(fp), []).append(rep.id)
        if len(fps) > 1:
            detail = "; ".join("%s=%s" % (fp, ",".join(sorted(ids)))
                               for fp, ids in sorted(fps.items()))
            raise MXNetError(
                "fleet: refusing split for model %r across mixed "
                "parameter layouts (%s) — reshard the artifact "
                "(tools/reshard.py) so every replica in the split "
                "agrees on one layout fingerprint" % (model, detail))

    def set_split(self, model, weights):
        """Set the version traffic split for ``model`` (weights are
        normalized; a missing version gets zero traffic)."""
        self._require_journal_writable()
        clean = {}
        for v, w in dict(weights).items():
            w = float(w)
            if w < 0:
                raise MXNetError("fleet: negative split weight %r for "
                                 "version %r" % (w, v))
            clean[str(v)] = w
        total = sum(clean.values())
        if total <= 0:
            raise MXNetError("fleet: split weights must sum > 0")
        norm = {v: w / total for v, w in clean.items()}
        self._refuse_mixed_layouts(str(model), set(norm))
        # WAL discipline: the record hits the disk before the split is
        # live, so an acked split is always durable (the drill asserts
        # acked control ops survive a primary disk death)
        self._journal_append("split", {"model": str(model),
                                       "weights": norm}, required=True)
        with self._lock:
            self.splits[str(model)] = norm
        return dict(norm)

    def clear_split(self, model):
        self._require_journal_writable()
        self._journal_append("split", {"model": str(model),
                                       "weights": None}, required=True)
        with self._lock:
            self.splits.pop(str(model), None)

    def promote(self, model, version):
        """Blue/green flip: 100% of ``model`` traffic to ``version``.
        Old-version replicas stay registered (instant rollback path);
        their in-flight requests finish — the router just stops handing
        them new ones."""
        self._require_journal_writable()
        model, version = str(model), str(version)
        self._journal_append("split", {"model": model,
                                       "weights": {version: 1.0}},
                             required=True)
        with self._lock:
            self.splits[model] = {version: 1.0}
            c = self.canaries.get(model)
            if c is not None and c["version"] == version:
                c["state"] = "promoted"
            c_rec = ({k: v for k, v in c.items() if k != "deltas"}
                     if c is not None else None)
        if c_rec is not None:
            self._journal_append("canary", {"model": model,
                                            "record": c_rec})
        return {"model": model, "split": {version: 1.0}}

    def start_canary(self, model, version, split=0.1, budget=None):
        """Send ``split`` of ``model`` traffic to ``version``; keep the
        previous split as the rollback baseline. ``budget`` defaults to
        the int8 accuracy budget flag — the PR-10 probe's top-1 delta
        is the rollback signal."""
        self._require_journal_writable()
        model, version = str(model), str(version)
        split = float(split)
        if not 0.0 < split < 1.0:
            raise MXNetError("fleet: canary split must be in (0, 1)")
        if budget is None:
            budget = flags.quant_accuracy_budget
        with self._lock:
            baseline = dict(self.splits.get(model) or {})
        if not baseline:
            versions = sorted(v for v in
                              self.registry.models().get(model, {})
                              if v != version)
            if not versions:
                raise MXNetError(
                    "fleet: no baseline version of %r to canary "
                    "against" % model)
            baseline = {v: 1.0 / len(versions) for v in versions}
        mixed = {v: w * (1.0 - split) for v, w in baseline.items()}
        mixed[version] = mixed.get(version, 0.0) + split
        self._refuse_mixed_layouts(model, set(mixed))
        record = {
            "model": model, "version": version, "split": split,
            "budget": float(budget), "baseline": baseline,
            "state": "active", "reason": None,
        }
        # WAL discipline (the set_split pattern): both records hit the
        # disk before the canary is live, and the fsync happens outside
        # the routing lock so request threads never stall on it
        self._journal_append("split", {"model": model,
                                       "weights": dict(mixed)},
                             required=True)
        self._journal_append("canary", {"model": model,
                                        "record": dict(record)},
                             required=True)
        with self._lock:
            self.splits[model] = mixed
            self.canaries[model] = dict(record, deltas=[])
        return dict(record, deltas=[])

    def report_canary(self, model, delta, version=None):
        """Feed one accuracy-probe delta (f32-vs-canary top-1 delta,
        ``tools/serve_loadgen.py --accuracy-probe`` shape). Exceeding
        the budget triggers automatic rollback: the canary version's
        weight goes to ZERO (baseline split restored) and its replicas
        are put in router-side draining — new traffic stops instantly,
        in-flight requests complete on the still-running processes, so
        nothing drops."""
        self._require_journal_writable()
        model = str(model)
        with self._lock:
            c = self.canaries.get(model)
            if c is None or c["state"] != "active":
                raise MXNetError(
                    "fleet: no active canary for model %r" % model)
            if version is not None and str(version) != c["version"]:
                raise MXNetError(
                    "fleet: canary for %r is version %r, not %r"
                    % (model, c["version"], version))
            delta = float(delta)
            c["deltas"].append(delta)
            if abs(delta) <= c["budget"]:
                return {"state": "active", "action": "none",
                        "delta": delta, "budget": c["budget"]}
            # decide the rollback under the lock but apply nothing yet:
            # the journal write comes first, and it must not run inside
            # the routing lock (it fsyncs)
            reason = ("accuracy delta %.6f exceeds budget %.6f"
                      % (delta, c["budget"]))
            new_split = {v: w for v, w in c["baseline"].items()
                         if v != c["version"]} or dict(c["baseline"])
            canary_version = c["version"]
            budget = c["budget"]
            rec = {k: v for k, v in c.items() if k != "deltas"}
            rec["state"] = "rolled_back"
            rec["reason"] = reason
        # journal-first, and required: a rollback ack must be durable
        # (a crash after the ack replays to the rolled-back split)
        self._journal_append("split", {"model": model,
                                       "weights": dict(new_split)},
                             required=True)
        self._journal_append("canary", {"model": model, "record": rec},
                             required=True)
        with self._lock:
            # revalidate: a concurrent promote/rollback between the two
            # critical sections wins; never clobber its state
            c2 = self.canaries.get(model)
            if c2 is c and c2["state"] == "active":
                c2["state"] = "rolled_back"
                c2["reason"] = reason
                self.splits[model] = new_split
        self._c_rollbacks.inc()
        drained = []
        for rep in self.registry.live_replicas():
            if rep.model == model and rep.version == canary_version:
                self.registry.set_draining(rep.id)
                drained.append(rep.id)
        return {"state": "rolled_back", "action": "rollback",
                "delta": delta, "budget": budget, "reason": reason,
                "drained_replicas": drained}

    # -- observability ------------------------------------------------------
    def federated_metrics(self):
        """The fleet ``/metrics`` body: every live replica's exposition
        merged under ``replica=<id>`` labels, plus the router's own
        series as ``replica="router"``."""
        sources = [("router", telemetry.prometheus_text())]
        errors = {}
        for rep in self.registry.live_replicas():
            try:
                sources.append((rep.id, self._scrape(rep.url)))
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError) as e:
                errors[rep.id] = str(e)
        text, skipped = federate.merge_expositions(sources)
        for sid, err in skipped:
            errors[sid] = "unparseable exposition: %s" % err
        return text, errors

    def fleet_snapshot(self):
        self.registry.sweep()
        with self._lock:
            splits = {m: dict(s) for m, s in self.splits.items()}
            canaries = {m: {k: v for k, v in c.items() if k != "deltas"}
                        for m, c in self.canaries.items()}
            sessions = {
                "open": sum(1 for s in self._sessions.values()
                            if not s.get("orphan")),
                "orphaned": sum(1 for s in self._sessions.values()
                                if s.get("orphan")),
            }
            autoscale = {k: dict(v)
                         for k, v in self.autoscale_state.items()}
        snap = self.registry.snapshot()
        snap["splits"] = splits
        snap["canaries"] = canaries
        snap["models"] = self.registry.models()
        snap["epoch"] = self.epoch
        snap["sessions"] = sessions
        if autoscale:
            snap["autoscale"] = autoscale
        if self.journal is not None:
            snap["journal"] = self.journal.stats()
            snap["journal_degraded"] = self.journal_degraded
            if self.degraded_reason:
                snap["journal_degraded_reason"] = self.degraded_reason
        if self.replay_stats is not None:
            snap["replay"] = dict(self.replay_stats)
        return snap


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n).decode() or "{}")

    def _fence(self, payload):
        """Epoch fence for control-plane writes: a caller that names a
        ``fleet_epoch`` other than ours is acting on a stale view of
        who the primary is (demoted router, old operator script) — 409,
        never a silent apply. A payload without the field is accepted:
        pre-fence clients keep working, they just don't get the
        protection. Returns True when the request may proceed."""
        claimed = payload.pop("fleet_epoch", None)
        router = self.server.mx_router
        if claimed is None or router.epoch is None:
            return True
        if int(claimed) != int(router.epoch):
            self._reply(409, {"error": "stale fleet_epoch %s (current "
                                       "epoch %s)" % (claimed,
                                                      router.epoch),
                              "epoch": router.epoch})
            return False
        return True

    def do_GET(self):
        router = self.server.mx_router
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            accept = self.headers.get("Accept", "")
            wants_prom = ("format=prometheus" in query
                          or ("text/plain" in accept
                              and "application/json" not in accept))
            if wants_prom:
                text, errors = router.federated_metrics()
                if errors:
                    text += "# fleet: %d replica scrapes failed\n" \
                        % len(errors)
                data = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 telemetry.prom.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(200, router.fleet_snapshot())
        elif path == "/fleet":
            self._reply(200, router.fleet_snapshot())
        elif path == "/healthz":
            snap = router.registry.snapshot()
            ok = snap["counts"]["ready"] > 0
            self._reply(200 if ok else 503,
                        {"status": "ok" if ok else "no_ready_replicas",
                         "replicas": snap["counts"]})
        elif path == "/readyz":
            snap = router.registry.snapshot()
            ok = snap["counts"]["ready"] > 0
            self._reply(200 if ok else 503,
                        {"ready": ok, "replicas": snap["counts"]})
        elif path == "/livez":
            self._reply(200, {"alive": True})
        elif path == "/journal/manifest":
            man = router.journal_manifest()
            if man is None:
                self._reply(404, {"error": "no journal attached"})
            else:
                self._reply(200, man)
        elif path in ("/journal/segment", "/journal/snapshot"):
            q = urllib.parse.parse_qs(query)
            name = (q.get("name") or [""])[0]
            try:
                offset = int((q.get("offset") or ["0"])[0])
                data = router.journal_read(name, offset)
            except (KeyError, ValueError) as e:
                self._reply(404, {"error": str(e)})
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(data)))
            if router.epoch is not None:
                # the fence rides every replication response: a pull
                # from a demoted primary is detectable per fetch, not
                # just per manifest poll
                self.send_header("X-Fleet-Epoch", str(router.epoch))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._reply(404, {"error": "no such endpoint %r" % self.path})

    def do_POST(self):
        router = self.server.mx_router
        try:
            payload = self._read_json()
        except ValueError as e:
            self._reply(400, {"error": "bad json: %s" % e})
            return
        # every control-plane mutation goes through the fence; the
        # data-plane /v1 routes are fenced per-replica by serve/http
        if self.path.startswith(("/fleet/", "/admin/")) \
                and not self._fence(payload):
            return
        try:
            if self.path in ("/v1/predict", "/predict"):
                code, out, headers = router.route_predict(payload)
                self._reply(code, out, headers)
            elif self.path in ("/v1/generate", "/generate"):
                code, out, headers = router.route_generate(payload)
                self._reply(code, out, headers)
            elif self.path in ("/v1/recommend", "/recommend"):
                code, out, headers = router.route_recommend(payload)
                self._reply(code, out, headers)
            elif self.path == "/fleet/register":
                rep = router.registry.register(payload)
                # the epoch rides every control-plane reply (when this
                # router is journaled): replicas learn the fence
                # passively and reject stale writers
                out = {"registered": rep.id}
                if router.epoch is not None:
                    out["epoch"] = router.epoch
                self._reply(200, out)
            elif self.path == "/fleet/heartbeat":
                known = router.registry.heartbeat(
                    payload.get("id"), ready=payload.get("ready"),
                    reason=payload.get("reason"),
                    load=payload.get("load"))
                out = {"known": known}
                if router.epoch is not None:
                    out["epoch"] = router.epoch
                self._reply(200, out)
            elif self.path == "/fleet/deregister":
                router.registry.deregister(payload.get("id"))
                out = {"deregistered": True}
                if router.epoch is not None:
                    out["epoch"] = router.epoch
                self._reply(200, out)
            elif self.path == "/admin/split":
                split = router.set_split(payload["model"],
                                         payload["weights"])
                self._reply(200, {"model": payload["model"],
                                  "split": split})
            elif self.path == "/admin/promote":
                self._reply(200, router.promote(payload["model"],
                                                payload["version"]))
            elif self.path == "/admin/canary":
                self._reply(200, router.start_canary(
                    payload["model"], payload["version"],
                    split=payload.get("split", 0.1),
                    budget=payload.get("budget")))
            elif self.path == "/admin/canary/report":
                self._reply(200, router.report_canary(
                    payload["model"], payload["delta"],
                    version=payload.get("version")))
            elif self.path == "/admin/drain":
                router._require_journal_writable()
                ok = router.registry.set_draining(
                    payload["id"], payload.get("draining", True))
                self._reply(200 if ok else 404,
                            {"id": payload["id"], "draining": ok})
            else:
                self._reply(404, {"error": "no such endpoint %r"
                                           % self.path})
        except JournalDegraded as e:
            # degraded control plane: not the client's fault and not
            # permanent — 503 + Retry-After, distinct from the 400s
            self._reply(503, {"error": str(e),
                              "retry_after_s": e.retry_after_s},
                        {"Retry-After":
                         "%d" % max(1, round(e.retry_after_s))})
        except (MXNetError, KeyError, TypeError, ValueError) as e:
            self._reply(400, {"error": str(e)})


class RouterHTTPFrontEnd:
    """Owns the router's ThreadingHTTPServer + accept thread (the same
    shape as serve/http.HttpFrontEnd, so tools share idiom)."""

    def __init__(self, router, host="127.0.0.1", port=8090, verbose=False):
        self.mx_router = router
        self.httpd = ThreadingHTTPServer((host, port), _RouterHandler)
        self.httpd.mx_router = router
        self.httpd.verbose = verbose
        self.httpd.daemon_threads = True
        self._thread = None

    @property
    def address(self):
        h, p = self.httpd.server_address[:2]
        return "http://%s:%d" % (h, p)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="mxtpu-fleet-http",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        # shutdown() blocks forever unless serve_forever is running, so a
        # never-started front end only needs its listen socket closed.
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)


def route_http(router, host="127.0.0.1", port=8090, verbose=False):
    """Start the fleet HTTP front end; returns the running
    :class:`RouterHTTPFrontEnd` (``.stop()`` to shut down)."""
    return RouterHTTPFrontEnd(router, host, port, verbose=verbose).start()
