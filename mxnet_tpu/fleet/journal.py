"""Write-ahead fleet journal: the router's durability layer.

The registry and every in-flight generate cursor used to live only in
the router process's memory (ROADMAP item-1 residual) — a router crash
dropped all sessions and cold-restarted the fleet. This module makes
the fleet *control plane* as recoverable as the data plane already is
(PR-2 checkpoints, PR-9 eviction cursors, PR-11 replica migration):

* **Append-only CRC-framed log.** Every registry mutation (register /
  heartbeat-derived readiness flips / drain / split / canary verdict)
  and every generate-session hop cursor is one record: an 8-byte
  ``<II`` header (payload length, crc32) followed by a JSON payload
  ``{"seq", "kind", "data"}``. Appends are fsync-batched (group commit
  every ``MXNET_FLEET_JOURNAL_SYNC_EVERY`` records; rare critical
  records pass ``sync=True``) so the hot decode path pays a buffered
  write, not a disk round-trip, per hop.
* **Snapshot + compaction.** ``compact(state)`` writes the full
  :class:`FleetState` as ``snap-<seq>.json`` with ``checkpoint.py``'s
  temp+fsync+rename discipline, rotates to a fresh ``wal-<n>.log``
  segment, and deletes everything older — restart replay is
  O(snapshot), not O(history).
* **Tolerant replay.** :func:`replay` loads the newest *valid*
  snapshot, then applies records in global order. A truncated tail
  record (SIGKILL mid-append) or a CRC mismatch stops that segment's
  scan without losing the prefix; records with ``seq <=`` the already
  applied sequence are skipped, so replaying twice — or replaying a
  snapshot plus the pre-compaction log — is idempotent.
* **Lease + tailing** for the warm standby: the primary touches
  ``lease.json`` every ``MXNET_FLEET_LEASE_INTERVAL_S``; the standby's
  :class:`JournalTailer` keeps a warm :class:`FleetState` and its
  :class:`LeaseMonitor` measures staleness as *monotonic time since
  the lease content last changed* — an NTP step can't trigger (or
  mask) a failover, the same reason the registry sweep is monotonic.

Losing the last few *unsynced* hop cursors is safe by construction:
resuming from an older cursor just regenerates more tokens, and
position-keyed sampling makes the stitched tail bitwise-equal either
way. What the journal must never lose silently is ordering, which the
monotone ``seq`` gives.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib

from ..checkpoint import atomic_replace
from .. import telemetry

__all__ = ["FleetJournal", "FleetState", "JournalTailer", "LeaseMonitor",
           "replay", "read_segment", "write_lease", "read_lease",
           "release_lease", "lease_holder_alive"]

_FRAME = struct.Struct("<II")           # payload length, crc32(payload)
_SEG_PREFIX, _SEG_SUFFIX = "wal-", ".log"
_SNAP_PREFIX, _SNAP_SUFFIX = "snap-", ".json"
_LEASE = "lease.json"


def _fire_fault(op, **ctx):
    """Storage fault hook (``enospc@journal=...`` / ``torn_write`` /
    ``slow_fsync`` in parallel/faultinject.py). Gated on the env var so
    a production router never pays the parallel-package import."""
    if not os.environ.get("MXNET_FAULT_INJECT"):
        return
    from ..parallel import faultinject
    faultinject.fire("journal", op=op, **ctx)


# ---------------------------------------------------------------------------
# state reducer
# ---------------------------------------------------------------------------

class FleetState:
    """The replayable fleet control-plane state: everything a freshly
    promoted router needs to route as if it were the crashed one.

    ``apply`` is a pure-ish reducer over journal records; it skips any
    record whose ``seq`` is not beyond ``applied_seq``, which is what
    makes double replay (and snapshot+tail replay) idempotent."""

    def __init__(self):
        self.applied_seq = 0
        self.epoch = 0               # highest fencing epoch journaled
        self.address = None          # last primary's bound URL
        self.replicas = {}           # rid -> registration info + state
        self.splits = {}             # model -> {version: weight}
        self.canaries = {}           # model -> canary record (no deltas)
        self.sessions = {}           # sid -> hop cursor record
        self.autoscale = {}          # scaler key -> {owned, last, ...}:
                                     # a promoted standby inherits which
                                     # replicas the autoscaler launched
                                     # and where its policy left off

    def apply(self, seq, kind, data):
        """Apply one record; returns False for stale (already-applied)
        sequence numbers."""
        seq = int(seq)
        if seq <= self.applied_seq:
            return False
        self.applied_seq = seq
        if kind == "epoch":
            self.epoch = max(self.epoch, int(data.get("epoch", 0)))
            if data.get("address"):
                self.address = data["address"]
        elif kind == "register":
            self.replicas[str(data["id"])] = dict(data)
        elif kind == "state":
            rep = self.replicas.get(str(data.get("id")))
            if rep is not None:
                rep.update({k: v for k, v in data.items() if k != "id"})
        elif kind == "deregister":
            self.replicas.pop(str(data.get("id")), None)
        elif kind == "split":
            if data.get("weights"):
                self.splits[str(data["model"])] = dict(data["weights"])
            else:
                self.splits.pop(str(data.get("model")), None)
        elif kind == "canary":
            if data.get("record"):
                self.canaries[str(data["model"])] = dict(data["record"])
            else:
                self.canaries.pop(str(data.get("model")), None)
        elif kind == "session":
            self.sessions[str(data["sid"])] = dict(data)
        elif kind == "session_done":
            self.sessions.pop(str(data.get("sid")), None)
        elif kind == "autoscale":
            # one record per scaling decision; the reducer keeps the
            # scaler's durable view (owned replica ids + last decision)
            key = str(data.get("scaler") or "default")
            rec = self.autoscale.setdefault(key, {})
            if "owned" in data:
                rec["owned"] = list(data["owned"] or [])
            rec["last"] = {k: v for k, v in data.items()
                           if k not in ("scaler", "owned")}
        # unknown kinds are skipped, not fatal: an older standby may
        # tail a newer primary's journal during a rolling upgrade
        return True

    def to_dict(self):
        return {
            "applied_seq": self.applied_seq,
            "epoch": self.epoch,
            "address": self.address,
            "replicas": {r: dict(v) for r, v in self.replicas.items()},
            "splits": {m: dict(w) for m, w in self.splits.items()},
            "canaries": {m: dict(c) for m, c in self.canaries.items()},
            "sessions": {s: dict(v) for s, v in self.sessions.items()},
            "autoscale": {k: dict(v)
                          for k, v in self.autoscale.items()},
        }

    @classmethod
    def from_dict(cls, d):
        st = cls()
        st.applied_seq = int(d.get("applied_seq", 0))
        st.epoch = int(d.get("epoch", 0))
        st.address = d.get("address")
        st.replicas = {str(r): dict(v)
                       for r, v in (d.get("replicas") or {}).items()}
        st.splits = {str(m): dict(w)
                     for m, w in (d.get("splits") or {}).items()}
        st.canaries = {str(m): dict(c)
                       for m, c in (d.get("canaries") or {}).items()}
        st.sessions = {str(s): dict(v)
                       for s, v in (d.get("sessions") or {}).items()}
        st.autoscale = {str(k): dict(v)
                        for k, v in (d.get("autoscale") or {}).items()}
        return st


# ---------------------------------------------------------------------------
# segment + snapshot file layout
# ---------------------------------------------------------------------------

def _segments(dir_):
    """Segment paths sorted by their rotation number (global record
    order: the journal only ever appends to the newest segment)."""
    out = []
    try:
        names = os.listdir(dir_)
    except OSError:
        return out
    for name in names:
        if name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
            try:
                n = int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
            except ValueError:
                continue
            out.append((n, os.path.join(dir_, name)))
    return sorted(out)


def _snapshots(dir_):
    out = []
    try:
        names = os.listdir(dir_)
    except OSError:
        return out
    for name in names:
        if name.startswith(_SNAP_PREFIX) and name.endswith(_SNAP_SUFFIX):
            try:
                n = int(name[len(_SNAP_PREFIX):-len(_SNAP_SUFFIX)])
            except ValueError:
                continue
            out.append((n, os.path.join(dir_, name)))
    return sorted(out)


def read_segment(path, offset=0):
    """Read complete, CRC-valid records from ``path`` starting at byte
    ``offset``. Returns ``(records, new_offset, clean)`` where records
    are ``(seq, kind, data)`` tuples and ``new_offset`` points just past
    the last *good* record — a torn tail (short header/payload) or a
    CRC mismatch stops the scan there without losing the prefix, and a
    tailer retrying from ``new_offset`` picks the record up if its
    remaining bytes arrive later. ``clean`` is False when the scan
    stopped early."""
    records = []
    try:
        f = open(path, "rb")
    except OSError:
        return records, offset, True
    with f:
        f.seek(offset)
        pos = offset
        while True:
            header = f.read(_FRAME.size)
            if len(header) < _FRAME.size:
                return records, pos, len(header) == 0
            length, crc = _FRAME.unpack(header)
            payload = f.read(length)
            if len(payload) < length:
                return records, pos, False          # torn tail
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                return records, pos, False          # corrupt record
            try:
                rec = json.loads(payload.decode("utf-8"))
                records.append((int(rec["seq"]), str(rec["kind"]),
                                rec.get("data") or {}))
            except (ValueError, KeyError, TypeError):
                return records, pos, False
            pos += _FRAME.size + length


def replay(dir_):
    """Rebuild the :class:`FleetState` from ``dir_``: newest loadable
    snapshot first, then every record (from every surviving segment, in
    order) with ``seq`` beyond it. Returns ``(state, stats)``."""
    state = FleetState()
    stats = {"snapshot_seq": 0, "segments": 0, "records": 0,
             "stale_records": 0, "torn_segments": 0}
    for _, snap_path in reversed(_snapshots(dir_)):
        try:
            with open(snap_path) as f:
                state = FleetState.from_dict(json.load(f))
            stats["snapshot_seq"] = state.applied_seq
            break
        except (OSError, ValueError, KeyError, TypeError):
            continue       # half-written pre-atomic_replace leftovers
    for _, seg_path in _segments(dir_):
        stats["segments"] += 1
        records, _, clean = read_segment(seg_path)
        if not clean:
            stats["torn_segments"] += 1
        for seq, kind, data in records:
            if state.apply(seq, kind, data):
                stats["records"] += 1
            else:
                stats["stale_records"] += 1
    return state, stats


# ---------------------------------------------------------------------------
# the journal (writer side)
# ---------------------------------------------------------------------------

class FleetJournal:
    """Append-only writer over a journal directory.

    One instance per *primary* router. ``start_seq`` continues the
    sequence numbering from a replayed state; every open rotates to a
    fresh segment so an old incarnation's torn tail is never appended
    through."""

    def __init__(self, dir_, start_seq=0, sync_every=None,
                 segment_bytes=None):
        from ..config import flags
        if sync_every is None:
            sync_every = flags.fleet_journal_sync_every
        if segment_bytes is None:
            segment_bytes = flags.fleet_journal_segment_mb * (1 << 20)
        self.dir = os.fspath(dir_)
        os.makedirs(self.dir, exist_ok=True)
        self.sync_every = max(1, int(sync_every))
        self.segment_bytes = max(0, int(segment_bytes))
        self._lock = threading.Lock()
        self._seq = int(start_seq)
        self._unsynced = 0
        self._seg_bytes = 0
        self._dirty_tail = False
        self.records_since_compact = 0
        segs = _segments(self.dir)
        seg_no = (segs[-1][0] + 1) if segs else 1
        self._seg_path = os.path.join(
            self.dir, "%s%08d%s" % (_SEG_PREFIX, seg_no, _SEG_SUFFIX))
        self._f = open(self._seg_path, "ab", buffering=0)
        reg = telemetry.default_registry()
        self._c_records = reg.counter(
            "fleet/journal_records", "Records appended to the fleet "
            "write-ahead journal, by kind.")
        self._c_bytes = reg.counter(
            "fleet/journal_bytes", "Bytes appended to the fleet journal.")
        self._c_fsyncs = reg.counter(
            "fleet/journal_fsyncs", "Journal fsync batches (group "
            "commits + explicit syncs).")
        self._c_compactions = reg.counter(
            "fleet/journal_compactions",
            "Snapshot+truncate compactions of the fleet journal.")
        self._c_rotations = reg.counter(
            "fleet/journal_rotations",
            "Size-based segment rotations "
            "(MXNET_FLEET_JOURNAL_SEGMENT_MB).")
        self._c_write_errors = reg.counter(
            "fleet/journal_write_errors",
            "Failed journal writes/fsyncs (ENOSPC, torn writes, dead "
            "disks) surfaced to the primary.")

    @property
    def seq(self):
        with self._lock:
            return self._seq

    def append(self, kind, data, sync=False):
        """Append one record; returns its sequence number. ``sync``
        forces an immediate fsync (epoch records, registrations);
        otherwise the fsync is batched every ``sync_every`` appends.

        A failed write does NOT consume a sequence number (a burned
        seq would read as a gap to replicating standbys) and marks the
        tail dirty: the next append first truncates back to the last
        whole record, so a torn frame is never appended through —
        replay would stop at the garbage and silently drop everything
        after it. Storage failures (real or injected) surface to the
        caller as ``OSError``; the router turns that into degraded
        mode rather than crashing the data plane."""
        with self._lock:
            seq = self._seq + 1
            payload = json.dumps(
                {"seq": seq, "kind": kind, "data": data},
                sort_keys=True).encode("utf-8")
            frame = _FRAME.pack(
                len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
            try:
                if self._dirty_tail:
                    self._f.truncate(self._seg_bytes)
                    self._dirty_tail = False
                _fire_fault("append", kind=kind, path=self._seg_path)
                self._f.write(frame)
            except OSError as e:
                keep = getattr(e, "keep_bytes", None)
                if keep is not None:
                    # torn write: part of the frame reaches the disk
                    try:
                        self._f.write(
                            frame[:max(0, min(keep, len(frame) - 1))])
                    except OSError:
                        pass
                self._dirty_tail = True
                self._c_write_errors.inc()
                raise
            self._seq = seq
            self._seg_bytes += len(frame)
            self._unsynced += 1
            self.records_since_compact += 1
            if sync or self._unsynced >= self.sync_every:
                self._fsync_locked()
            if self.segment_bytes and self._seg_bytes >= self.segment_bytes:
                try:
                    self._rotate_locked()
                except OSError:
                    # rotation is a bound, not correctness: stay on the
                    # oversized segment; the next group commit surfaces
                    # the sick disk as a failed append
                    self._c_write_errors.inc()
        self._c_records.inc(kind=kind)
        self._c_bytes.inc(len(frame))
        return seq

    def _fsync_locked(self):
        _fire_fault("fsync", path=self._seg_path)
        os.fsync(self._f.fileno())
        self._unsynced = 0
        self._c_fsyncs.inc()

    def _rotate_locked(self):
        """Seal the live segment (fsync) and continue in a fresh one.
        Size-based rotation bounds the unit of cross-host replication
        and the blast radius of a torn tail to one segment."""
        self._fsync_locked()
        segs = _segments(self.dir)
        seg_no = (segs[-1][0] + 1) if segs else 1
        new_path = os.path.join(
            self.dir, "%s%08d%s" % (_SEG_PREFIX, seg_no, _SEG_SUFFIX))
        new_f = open(new_path, "ab", buffering=0)
        old_f = self._f
        self._f, self._seg_path = new_f, new_path
        self._seg_bytes = 0
        self._dirty_tail = False
        old_f.close()
        self._c_rotations.inc()

    def sync(self):
        """Flush the current group commit to disk."""
        with self._lock:
            if self._unsynced:
                self._fsync_locked()

    def compact(self, state):
        """Durably snapshot ``state`` and truncate history: fsync the
        log, write ``snap-<seq>.json`` (temp + fsync + rename — the
        checkpoint.py discipline), rotate to a fresh segment, delete
        older segments and snapshots. Replay after this is O(snapshot)
        plus whatever lands in the new segment."""
        if isinstance(state, FleetState):
            state = state.to_dict()
        with self._lock:
            _fire_fault("compact", path=self.dir)
            self._fsync_locked()
            seq = self._seq
            state = dict(state, applied_seq=seq)
            snap_path = os.path.join(
                self.dir, "%s%016d%s" % (_SNAP_PREFIX, seq, _SNAP_SUFFIX))
            with atomic_replace(snap_path) as tmp:
                with open(tmp, "w") as f:
                    json.dump(state, f, sort_keys=True)
            old_f, old_seg = self._f, self._seg_path
            segs = _segments(self.dir)
            seg_no = (segs[-1][0] + 1) if segs else 1
            self._seg_path = os.path.join(
                self.dir, "%s%08d%s" % (_SEG_PREFIX, seg_no, _SEG_SUFFIX))
            self._f = open(self._seg_path, "ab", buffering=0)
            self._seg_bytes = 0
            self._dirty_tail = False
            old_f.close()
            for _, p in segs:
                if p != self._seg_path:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            for _, p in _snapshots(self.dir):
                if p != snap_path:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            self.records_since_compact = 0
        self._c_compactions.inc()
        return snap_path

    def stats(self):
        with self._lock:
            return {"dir": self.dir, "seq": self._seq,
                    "segment": os.path.basename(self._seg_path),
                    "segment_bytes": self._seg_bytes,
                    "unsynced": self._unsynced,
                    "records_since_compact": self.records_since_compact,
                    "sync_every": self.sync_every,
                    "rotate_at_bytes": self.segment_bytes}

    def close(self):
        with self._lock:
            try:
                self._fsync_locked()
            except (OSError, ValueError):
                pass
            self._f.close()


# ---------------------------------------------------------------------------
# tailer (standby side)
# ---------------------------------------------------------------------------

class JournalTailer:
    """Incrementally replays a journal directory someone else writes:
    the warm standby's view of the fleet. Remembers a byte offset per
    segment so each poll reads only new bytes; a torn tail simply stops
    that segment's scan until more bytes arrive (the primary may be
    mid-append), and a newer snapshot (compaction) is adopted whenever
    it is ahead of what was already applied. :meth:`next_delay_s`
    paces the caller's poll loop: immediate re-poll after progress,
    capped jittered exponential backoff while idle."""

    def __init__(self, dir_, idle_base_s=0.01, idle_cap_s=None):
        if idle_cap_s is None:
            from ..config import flags
            idle_cap_s = flags.fleet_standby_poll_s
        self.dir = os.fspath(dir_)
        self.state = FleetState()
        self.idle_base_s = max(1e-4, float(idle_base_s))
        self.idle_cap_s = max(self.idle_base_s, float(idle_cap_s))
        self._offsets = {}
        self._empty_polls = 0
        self._gap = False

    def next_delay_s(self, rng=None):
        """Suggested sleep before the next :meth:`poll`: 0 right after
        a poll that applied records (catch-up burst — drain a backlog
        at full speed), then capped jittered exponential backoff while
        idle. An idle standby neither spins at the poll interval nor
        lags a suddenly-busy primary by more than ``idle_cap_s``."""
        if self._empty_polls == 0:
            return 0.0
        from .supervisor import backoff_delay
        return min(self.idle_cap_s,
                   backoff_delay(self._empty_polls - 1,
                                 base=self.idle_base_s,
                                 cap=self.idle_cap_s, jitter=0.25,
                                 rng=rng))

    def poll(self):
        """Apply everything new; returns the number of records applied.

        Gap-safe against a racing compaction: if a segment scan lands
        past a compaction (its first new record's seq jumps beyond
        ``applied_seq + 1`` because the records in between were folded
        into a snapshot and their segments deleted mid-poll), nothing
        is applied across the gap — the covering snapshot (compaction
        writes it *before* deleting segments) is adopted on an
        immediate second pass and the scan resumes contiguously."""
        applied = self._poll_once()
        if self._gap:
            applied += self._poll_once()
        self._empty_polls = 0 if applied else min(self._empty_polls + 1,
                                                  32)
        return applied

    def _poll_once(self):
        applied = 0
        self._gap = False
        for snap_seq, snap_path in reversed(_snapshots(self.dir)):
            if snap_seq <= self.state.applied_seq:
                break
            try:
                with open(snap_path) as f:
                    self.state = FleetState.from_dict(json.load(f))
                self._offsets.clear()
                applied += 1
                break
            except (OSError, ValueError, KeyError, TypeError):
                continue
        live = set()
        for _, seg_path in _segments(self.dir):
            live.add(seg_path)
            off = self._offsets.get(seg_path, 0)
            records, new_off, _clean = read_segment(seg_path, off)
            gap_here = False
            for seq, kind, data in records:
                if self.state.applied_seq and \
                        seq > self.state.applied_seq + 1:
                    gap_here = True
                    break
                if self.state.apply(seq, kind, data):
                    applied += 1
            if gap_here:
                # records jumped past a compaction; keep the offset so
                # this batch is re-scanned (idempotently) after the
                # covering snapshot is adopted
                self._gap = True
            else:
                self._offsets[seg_path] = new_off
        for path in list(self._offsets):
            if path not in live:
                del self._offsets[path]         # compacted away
        return applied


# ---------------------------------------------------------------------------
# lease (primary liveness signal for the standby)
# ---------------------------------------------------------------------------

def _lease_path(dir_):
    return os.path.join(os.fspath(dir_), _LEASE)


def write_lease(dir_, payload):
    """Refresh the primary's lease: the payload plus a monotone beat
    counter, written via rename so readers never see a torn file. No
    fsync — the lease is a liveness signal, not durable state; what
    matters is that its *content changes* while the primary lives."""
    path = _lease_path(dir_)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    data = dict(payload)
    data["beat"] = data.get("beat", 0)
    with open(tmp, "w") as f:
        json.dump(data, f, sort_keys=True)
    os.replace(tmp, path)
    return path


def read_lease(dir_):
    try:
        with open(_lease_path(dir_), "rb") as f:
            raw = f.read()
        return json.loads(raw.decode("utf-8")), raw
    except (OSError, ValueError):
        return None, None


def release_lease(dir_):
    try:
        os.unlink(_lease_path(dir_))
        return True
    except OSError:
        return False


def lease_holder_alive(dir_, wait_s):
    """Startup guard for a would-be primary: sample the lease twice
    ``wait_s`` apart and call the holder alive iff the content changed
    (a live primary beats every MXNET_FLEET_LEASE_INTERVAL_S). Content
    comparison, not mtime-vs-wall-clock — immune to NTP steps and to
    stale lease files left by a SIGKILLed primary."""
    first, raw0 = read_lease(dir_)
    if first is None:
        return False
    time.sleep(max(0.0, float(wait_s)))
    _second, raw1 = read_lease(dir_)
    return raw1 is not None and raw1 != raw0


class LeaseMonitor:
    """Standby-side lease staleness: monotonic seconds since the lease
    content was last *observed to change*. A missing lease counts as
    unchanged (the clock keeps running), so a primary that dies before
    its first beat still fails over."""

    def __init__(self, dir_):
        self.dir = os.fspath(dir_)
        self._last_raw = read_lease(self.dir)[1]
        self._changed_at = time.monotonic()

    def age_s(self):
        raw = read_lease(self.dir)[1]
        if raw is not None and raw != self._last_raw:
            self._last_raw = raw
            self._changed_at = time.monotonic()
        return time.monotonic() - self._changed_at

    def expired(self, timeout_s):
        return self.age_s() > float(timeout_s)
