"""Demand-driven autoscaling: the router watches its own federated
load signals and resizes the fleet (ROADMAP item 3, layer 2).

The demand signal is NOT a new heuristic: every replica's heartbeat
already carries a perfmodel-derived load summary (``load_s`` = seconds
of queued work, ``unit_s`` = marginal seconds per request,
``queue_depth`` — the same ``perfmodel.roofline_seconds`` numbers the
replica's own admission control uses). The autoscaler folds those into
one pressure number — mean queue-seconds per in-rotation replica — and
applies a deliberately boring control policy:

* **Hysteresis**: a watermark must stay breached for
  ``breach_rounds`` consecutive ticks before anything happens, so a
  single-tick spike doesn't thrash the fleet.
* **Cooldown**: after any action the scaler holds for ``cooldown_s``
  (journaled as ``held:cooldown``), long enough for the action's
  effect to show up in the demand signal.
* **Break-even**: scale-up must pay for itself. With ``n`` replicas
  sharing ``W`` queue-seconds, adding one drains
  ``W/n - W/(n+1)`` seconds of per-replica backlog; if that gain is
  below ``startup_cost_s`` (spawn + artifact load + warmup) the spike
  will be over before the new replica is warm, so the scaler holds
  (``held:break_even``).

Actions ride the machinery earlier PRs built rather than inventing a
parallel path: scale-up asks the :class:`ReplicaSupervisor` to launch
a ``tools/serve.py --register`` process (PR-13); scale-down puts the
victim in router-side draining — new traffic stops instantly,
in-flight requests finish, decode sessions migrate bitwise via their
eviction cursors (PR-9/PR-11) — and only then SIGTERMs the process
(whose own graceful path deregisters and drains its front end). Every
decision is journaled through the fleet WAL (PR-14) with the scaler's
owned-replica set, so a promoted standby inherits scaling state and
keeps managing the same processes' registrations.

One :class:`Autoscaler` manages one model; run several against the
same router/supervisor for a mixed fleet.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry
from ..config import flags

__all__ = ["AutoscalePolicy", "Autoscaler"]


class AutoscalePolicy:
    """Tunables for one scaler; defaults come from the
    ``MXNET_AUTOSCALE_*`` flag registry (config.py)."""

    def __init__(self, min_replicas=None, max_replicas=None,
                 high_watermark_s=None, low_watermark_s=None,
                 breach_rounds=None, cooldown_s=None,
                 startup_cost_s=None, interval_s=None,
                 launch_timeout_s=30.0, page_high_occupancy=None,
                 deadline_headroom=None):
        def _f(v, flag):
            return flag if v is None else v
        self.min_replicas = int(_f(min_replicas,
                                   flags.autoscale_min_replicas))
        self.max_replicas = int(_f(max_replicas,
                                   flags.autoscale_max_replicas))
        self.high_watermark_s = float(_f(high_watermark_s,
                                         flags.autoscale_high_watermark_s))
        self.low_watermark_s = float(_f(low_watermark_s,
                                        flags.autoscale_low_watermark_s))
        self.breach_rounds = int(_f(breach_rounds,
                                    flags.autoscale_breach_rounds))
        self.cooldown_s = float(_f(cooldown_s,
                                   flags.autoscale_cooldown_s))
        self.startup_cost_s = float(_f(startup_cost_s,
                                       flags.autoscale_startup_cost_s))
        self.interval_s = float(_f(interval_s,
                                   flags.autoscale_interval_s))
        # decode memory / tail-latency pressure: either signal hot
        # counts as a high-watermark breach (see step())
        self.page_high_occupancy = float(
            _f(page_high_occupancy, flags.autoscale_page_high_occupancy))
        self.deadline_headroom = float(
            _f(deadline_headroom, flags.autoscale_deadline_headroom))
        # a launched process that never registers stops counting as
        # capacity after this long (crash loops must not wedge scaling)
        self.launch_timeout_s = float(launch_timeout_s)
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                "autoscale: need 0 <= min_replicas <= max_replicas, "
                "got %d..%d" % (self.min_replicas, self.max_replicas))

    def to_dict(self):
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "high_watermark_s": self.high_watermark_s,
            "low_watermark_s": self.low_watermark_s,
            "breach_rounds": self.breach_rounds,
            "cooldown_s": self.cooldown_s,
            "startup_cost_s": self.startup_cost_s,
            "interval_s": self.interval_s,
            "page_high_occupancy": self.page_high_occupancy,
            "deadline_headroom": self.deadline_headroom,
        }


class Autoscaler:
    """One model's scaling loop.

    ``spec_factory(replica_id)`` must return a
    :class:`~mxnet_tpu.fleet.supervisor.ReplicaSpec` whose argv serves
    the model and registers with this router (tools/route.py builds it
    from an argv template). ``supervisor`` launches/stops those
    processes; ``router`` supplies the registry (demand signal) and
    the journal (durability). ``clock`` is injectable for tests."""

    def __init__(self, router, supervisor, spec_factory, model,
                 policy=None, scaler=None, clock=time.monotonic):
        self.router = router
        self.supervisor = supervisor
        self.spec_factory = spec_factory
        self.model = str(model)
        self.policy = policy or AutoscalePolicy()
        self.scaler = str(scaler or self.model)
        self.clock = clock
        self.owned = set()        # replica ids this scaler launched
        self._pending = {}        # rid -> launch deadline (not yet registered)
        self._draining = set()    # rids drained, waiting to go idle
        self._breach_high = 0
        self._breach_low = 0
        self._last_action_t = None
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        reg = telemetry.default_registry()
        self._c_up = reg.counter(
            "autoscale/scale_up_total",
            "Replica launches decided by the autoscaler.")
        self._c_down = reg.counter(
            "autoscale/scale_down_total",
            "Replica drains decided by the autoscaler.")
        self._c_held = reg.counter(
            "autoscale/held_total",
            "Autoscaler actions suppressed by cooldown or the "
            "perfmodel break-even test.")
        self._g_desired = reg.gauge(
            "autoscale/desired_replicas",
            "Replica count the autoscaler is currently steering "
            "toward for its model.")
        self._g_pressure = reg.gauge(
            "autoscale/pressure_s",
            "Mean queue-seconds of work per in-rotation replica "
            "(the autoscaler's demand signal).")
        self._g_kv_occ = reg.gauge(
            "autoscale/kv_page_occupancy",
            "Worst in-rotation replica's KV page-pool occupancy "
            "(decode memory-pressure scale-out signal).")
        self._g_deadline = reg.gauge(
            "autoscale/deadline_ratio",
            "Worst in-rotation replica's p99 latency over its request "
            "deadline (tail-pressure scale-out signal).")
        self.restore()

    # -- durability ----------------------------------------------------------
    def restore(self):
        """Inherit scaling state from a replayed journal (standby
        promotion / supervised restart): the owned-replica set keeps
        meaning 'this scaler may drain these'."""
        st = getattr(self.router, "autoscale_state", {}) or {}
        rec = st.get(self.scaler)
        if rec:
            self.owned = set(str(r) for r in rec.get("owned") or [])
            last = rec.get("last") or {}
            if isinstance(last.get("seq"), int):
                self._seq = max(self._seq, int(last["seq"]))

    def _journal(self, action, reason, **extra):
        data = dict(scaler=self.scaler, model=self.model,
                    action=action, reason=reason, seq=self._seq,
                    owned=sorted(self.owned), **extra)
        try:
            self.router.record_autoscale(data)
        except Exception:
            # a degraded journal must not stop the control loop — the
            # decision still happened, it is just less durable
            pass
        telemetry.flight_recorder().record_event(
            "autoscale", scaler=self.scaler, model=self.model,
            action=action, reason=reason, **{
                k: v for k, v in extra.items()
                if isinstance(v, (int, float, str, bool, type(None)))})
        return data

    # -- demand signal -------------------------------------------------------
    def observe(self, now=None):
        """Fold registry state into the tick's demand picture."""
        now = self.clock() if now is None else now
        reps = [r for r in self.router.registry.replicas()
                if r.model == self.model and not r.dead]
        for r in reps:
            self._pending.pop(r.id, None)   # registered: launch landed
        for rid, deadline in list(self._pending.items()):
            if now > deadline:
                self._pending.pop(rid)
                self.owned.discard(rid)
        in_rot = [r for r in reps if r.ready and not r.draining]
        # registered but not (yet) ready: still warming its engines or
        # soft-pulled by a 503 — capacity that exists, just not
        # routable this tick. Counting it stops the floor check from
        # launching a fresh replica every tick of a warmup window.
        warming = [r for r in reps if not r.ready and not r.draining]
        load_s = sum(float(r.load.get("load_s", 0.0) or 0.0)
                     for r in in_rot)
        queue = sum(int(r.load.get("queue_depth", 0) or 0)
                    for r in in_rot)
        # worst-replica signals: page exhaustion and deadline pressure
        # are per-replica cliffs, so the max (not the mean) is the
        # demand picture — one page-starved replica is one replica
        # about to stall admissions
        kv_occ = max([float(r.load.get("kv_page_occupancy", 0.0) or 0.0)
                      for r in in_rot] or [0.0])
        deadline_ratio = 0.0
        for r in in_rot:
            p99 = float(r.load.get("p99_ms", 0.0) or 0.0)
            deadline = float(r.load.get("deadline_ms", 0.0) or 0.0)
            if p99 > 0 and deadline > 0:
                deadline_ratio = max(deadline_ratio, p99 / deadline)
        n_cap = len(in_rot) + len(warming) + len(self._pending)
        pressure = load_s / max(1, len(in_rot))
        return {
            "replicas": len(reps),
            "in_rotation": len(in_rot),
            "pending": len(self._pending),
            "capacity": n_cap,
            "load_s": round(load_s, 4),
            "queue_depth": queue,
            "pressure_s": round(pressure, 4),
            "kv_page_occupancy": round(kv_occ, 4),
            "deadline_ratio": round(deadline_ratio, 4),
        }

    # -- actions -------------------------------------------------------------
    def _launch(self, now, reason, obs):
        self._seq += 1
        rid = "%s-as%d" % (self.scaler, self._seq)
        spec = self.spec_factory(rid)
        self.supervisor.add(spec, start=True)
        self.owned.add(rid)
        self._pending[rid] = now + self.policy.launch_timeout_s
        self._last_action_t = now
        self._breach_high = self._breach_low = 0
        self._c_up.inc()
        return self._journal("scale_up", reason, replica=rid,
                             metrics=obs)

    def _start_drain(self, now, reason, obs):
        """Pick the least-loaded owned in-rotation replica and stop
        routing to it; the process keeps running until idle."""
        victims = [r for r in self.router.registry.replicas()
                   if r.id in self.owned and not r.dead
                   and not r.draining and r.ready]
        if not victims:
            return None
        victim = min(victims, key=lambda r: r.score())
        self.router.registry.set_draining(victim.id, True)
        self._draining.add(victim.id)
        self._seq += 1
        self._last_action_t = now
        self._breach_high = self._breach_low = 0
        self._c_down.inc()
        return self._journal("scale_down", reason, replica=victim.id,
                             metrics=obs)

    def _reap_drained(self):
        """SIGTERM drained replicas once idle (zero in-flight, empty
        queue): the serve.py graceful path deregisters, drains its
        front end, and exits; decode sessions already migrated via
        their eviction cursors when draining pulled it from rotation."""
        done = []
        for rid in sorted(self._draining):
            rep = self.router.registry.get(rid)
            if rep is not None and not rep.dead:
                busy = (rep.inflight > 0
                        or int(rep.load.get("queue_depth", 0) or 0) > 0)
                if busy:
                    continue
            try:
                self.supervisor.stop(rid, wait_s=5.0)
            except Exception:
                pass
            self._draining.discard(rid)
            self.owned.discard(rid)
            done.append(rid)
            self._seq += 1
            self._journal("drain_complete", "replica idle after drain",
                          replica=rid)
        return done

    # -- the control loop ----------------------------------------------------
    def step(self, now=None):
        """One tick: observe, decide, maybe act. Returns the decision
        dict (action in scale_up / scale_down / drain_complete /
        held:* / steady)."""
        now = self.clock() if now is None else now
        reaped = self._reap_drained()
        obs = self.observe(now)
        self._g_pressure.set(obs["pressure_s"])
        self._g_kv_occ.set(obs["kv_page_occupancy"])
        self._g_deadline.set(obs["deadline_ratio"])
        pol = self.policy

        # floor: a model below min_replicas gets capacity NOW —
        # no watermark, no cooldown, no break-even
        if obs["capacity"] < pol.min_replicas:
            return self._launch(now, "below min_replicas", obs)

        pressure = obs["pressure_s"]
        # page exhaustion / tail-vs-deadline are scale-out signals of
        # their own: they breach the high watermark even while mean
        # queue-seconds look calm (long contexts eat the KV pool, tail
        # latency creeps to the deadline) — and a hot fleet never
        # scales down
        page_hot = obs["kv_page_occupancy"] > pol.page_high_occupancy
        deadline_hot = obs["deadline_ratio"] > pol.deadline_headroom
        hot = page_hot or deadline_hot
        settled = (obs["pending"] == 0
                   and obs["in_rotation"] == obs["capacity"])
        if pressure > pol.high_watermark_s or hot:
            self._breach_high += 1
            self._breach_low = 0
        elif pressure < pol.low_watermark_s:
            # low readings from an unsettled fleet (launch pending /
            # replica warming) don't count toward a drain: the signal
            # reflects capacity that hasn't materialized yet
            if settled:
                self._breach_low += 1
            self._breach_high = 0
        else:
            self._breach_high = self._breach_low = 0

        want_up = (self._breach_high >= pol.breach_rounds
                   and obs["capacity"] < pol.max_replicas)
        # scale-down only from a SETTLED fleet: while a launch is
        # pending or a replica is warming, the low pressure reading is
        # an artifact of capacity that hasn't materialized — draining a
        # replica now (the warming one scores 0 and would be the
        # victim) turns every spike into a launch/drain storm
        want_down = (self._breach_low >= pol.breach_rounds
                     and obs["capacity"] > pol.min_replicas
                     and obs["pending"] == 0
                     and obs["in_rotation"] == obs["capacity"]
                     and bool(self.owned - self._draining))
        self._g_desired.set(obs["capacity"]
                            + (1 if want_up else 0)
                            - (1 if want_down else 0))
        if not (want_up or want_down):
            if reaped:
                return {"action": "drain_complete", "replicas": reaped}
            return {"action": "steady", "metrics": obs}

        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < pol.cooldown_s)
        if in_cooldown:
            self._c_held.inc()
            return self._journal(
                "held:cooldown",
                "action suppressed: %.1fs of %.1fs cooldown remain"
                % (pol.cooldown_s - (now - self._last_action_t),
                   pol.cooldown_s),
                wanted="scale_up" if want_up else "scale_down",
                metrics=obs)

        if want_up:
            if page_hot:
                return self._launch(
                    now, "kv page occupancy %.2f > %.2f for %d rounds "
                    "(memory pressure bypasses the break-even test: "
                    "waiting cannot free pages)"
                    % (obs["kv_page_occupancy"], pol.page_high_occupancy,
                       self._breach_high), obs)
            if deadline_hot:
                return self._launch(
                    now, "p99/deadline %.2f > %.2f for %d rounds (tail "
                    "about to expire requests; bypasses break-even)"
                    % (obs["deadline_ratio"], pol.deadline_headroom,
                       self._breach_high), obs)
            # break-even: adding a replica drains W/n - W/(n+1)
            # queue-seconds of per-replica backlog; below the startup
            # cost the spike outruns the launch
            n = max(1, obs["in_rotation"])
            gain_s = obs["load_s"] / n - obs["load_s"] / (n + 1)
            if gain_s <= pol.startup_cost_s:
                self._c_held.inc()
                return self._journal(
                    "held:break_even",
                    "projected drain gain %.2fs <= startup cost %.2fs"
                    % (gain_s, pol.startup_cost_s),
                    wanted="scale_up", metrics=obs)
            return self._launch(
                now, "pressure %.2fs > %.2fs for %d rounds; drain "
                "gain %.2fs beats startup %.2fs"
                % (pressure, pol.high_watermark_s, self._breach_high,
                   gain_s, pol.startup_cost_s), obs)

        return self._start_drain(
            now, "pressure %.2fs < %.2fs for %d rounds"
            % (pressure, pol.low_watermark_s, self._breach_low),
            obs) or {"action": "steady", "metrics": obs}

    # -- thread lifecycle ----------------------------------------------------
    def start(self, interval_s=None):
        interval_s = (self.policy.interval_s if interval_s is None
                      else float(interval_s))
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                try:
                    self.step()
                except Exception:
                    # one bad tick (registry race, spawn failure) must
                    # not kill the scaling loop
                    pass

        self._thread = threading.Thread(
            target=_loop, name="mxnet-autoscale-%s" % self.scaler,
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def snapshot(self):
        return {
            "scaler": self.scaler,
            "model": self.model,
            "owned": sorted(self.owned),
            "draining": sorted(self._draining),
            "pending": sorted(self._pending),
            "policy": self.policy.to_dict(),
        }
