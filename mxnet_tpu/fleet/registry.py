"""Replica registry: the router's authoritative view of the fleet.

Push-based, mirroring ``parallel/fault.py``'s heartbeat-file liveness
but over HTTP (replicas and router are separate hosts in production):
each ``tools/serve.py --register`` replica POSTs ``/fleet/register``
once, then ``/fleet/heartbeat`` every ``MXNET_FLEET_HEARTBEAT_S``
carrying its readiness (liveness != readiness — a draining or
engine-warming replica is alive but must leave rotation) and a
perfmodel-derived load summary (``load_s`` = estimated seconds of
queued work, ``unit_s`` = estimated seconds per additional request —
the same ``perfmodel.roofline_seconds`` numbers the replica's own
admission control uses, NOT a new router-side heuristic). A heartbeat
older than ``MXNET_FLEET_HEARTBEAT_TIMEOUT_S`` marks the replica dead,
exactly like a stale heartbeat file marks a training rank dead.

Identity matters for blue/green: a replica registers under a
``(model, version)`` pair plus the artifact's content hash
(:func:`mxnet_tpu.serving.artifact_identity`), so a traffic split is a
statement about *artifacts*, not processes.

Stdlib-only; the announcer half (replica side) is a thin urllib client.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

__all__ = ["Replica", "ReplicaRegistry", "ReplicaAnnouncer"]


class Replica:
    """One registered serving process, as the router sees it."""

    __slots__ = ("id", "url", "model", "version", "mode", "identity",
                 "pid", "registered_at", "last_heartbeat", "ready",
                 "reason", "load", "dead", "dead_reason", "draining",
                 "inflight", "served", "static", "spec", "layout")

    def __init__(self, rid, url, model, version, mode, identity=None,
                 pid=None, now=None):
        self.id = str(rid)
        self.url = str(url).rstrip("/")
        self.model = str(model)
        self.version = str(version)
        self.mode = str(mode)          # "predict" | "generate"
        self.identity = identity or {}
        self.pid = pid
        now = time.monotonic() if now is None else now
        self.registered_at = now
        self.last_heartbeat = now
        self.ready = False             # as reported by the replica
        self.reason = "registered"     # why not ready, when not
        self.load = {}                 # {"load_s", "unit_s", ...}
        self.dead = False
        self.dead_reason = None
        self.draining = False          # router-side: pulled from rotation
        self.inflight = 0              # router-side in-flight counter
        self.served = 0                # router-side routed-request count
        self.static = False            # seeded, no heartbeats: never swept
        self.spec = {}                 # generate wire geometry (e.g.
                                       # max_prompt_len caps hop chunking)
        self.layout = None             # artifact layout fingerprint
                                       # ({"fingerprint", "mesh"}): the
                                       # router refuses to split traffic
                                       # across disagreeing layouts

    def score(self):
        """Least-loaded routing score: estimated seconds of queued work
        on the replica plus the marginal cost of the requests this
        router already has in flight there. Both terms come from the
        replica's perfmodel-derived heartbeat."""
        load_s = float(self.load.get("load_s", 0.0) or 0.0)
        unit_s = float(self.load.get("unit_s", 0.0) or 0.0)
        return load_s + self.inflight * unit_s

    def snapshot(self, now=None):
        now = time.monotonic() if now is None else now
        return {
            "id": self.id, "url": self.url, "model": self.model,
            "version": self.version, "mode": self.mode,
            "identity": self.identity, "pid": self.pid,
            "ready": self.ready, "reason": self.reason,
            "dead": self.dead, "dead_reason": self.dead_reason,
            "draining": self.draining, "load": self.load,
            "inflight": self.inflight, "served": self.served,
            "layout": self.layout,
            "heartbeat_age_s": round(now - self.last_heartbeat, 3),
        }

    def to_info(self):
        """The registration-shaped dict the fleet journal records and
        :meth:`ReplicaRegistry.restore` consumes — everything needed to
        rebuild this entry in a promoted router."""
        return {
            "id": self.id, "url": self.url, "model": self.model,
            "version": self.version, "mode": self.mode,
            "identity": self.identity, "pid": self.pid,
            "ready": self.ready, "reason": self.reason,
            "dead": self.dead, "dead_reason": self.dead_reason,
            "draining": self.draining, "static": self.static,
            "spec": self.spec, "load": self.load,
            "layout": self.layout,
        }


class ReplicaRegistry:
    """Thread-safe replica table with heartbeat-staleness sweeping.

    Liveness bookkeeping is **monotonic by contract**: every timestamp
    comes from ``clock`` (default ``time.monotonic``), never the wall
    clock, so an NTP step cannot mass-expire a healthy fleet — the
    unit tests pin this with a patched clock. ``on_mutation(kind,
    data)``, when set (the router wires it to the fleet journal),
    observes every durable state change: registrations, readiness
    flips, deaths, drains, deregistrations."""

    def __init__(self, heartbeat_timeout_s=None, clock=None):
        if heartbeat_timeout_s is None:
            from ..config import flags
            heartbeat_timeout_s = flags.fleet_heartbeat_timeout_s
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._replicas = {}
        self.on_mutation = None

    def _notify(self, kind, data):
        # called with self._lock held so journal records preserve
        # mutation order; a plain buffered file append, never a device
        # sync or a join. A broken journal must not break routing.
        cb = self.on_mutation
        if cb is None:
            return
        try:
            cb(kind, data)
        except Exception as e:
            import sys
            print("fleet registry: mutation hook failed: %s" % e,
                  file=sys.stderr)

    def _publish_count(self):
        """Publish ``fleet/replica_count`` (total registered, the
        autoscaler's actual-vs-desired readback) and
        ``fleet/replicas_in_rotation`` (ready, non-draining). Called
        outside the lock on every membership/readiness change; a broken
        telemetry registry must never break registration."""
        try:
            from .. import telemetry
            with self._lock:
                total = len(self._replicas)
                ready = sum(1 for r in self._replicas.values()
                            if r.ready and not r.dead and not r.draining)
            telemetry.gauge(
                "fleet/replica_count",
                "Replicas currently registered with the router "
                "(any state)").set(total)
            telemetry.gauge(
                "fleet/replicas_in_rotation",
                "Registered replicas that are ready, alive, and not "
                "draining").set(ready)
        except Exception:
            pass

    # -- replica-driven lifecycle ------------------------------------------
    def register(self, info):
        """Upsert from a registration payload (dict with id/url/model/
        version/mode + optional identity/pid/ready/reason/load).
        Re-registration (a supervised restart reusing the id) resets
        death state."""
        rid = str(info["id"])
        with self._lock:
            rep = Replica(rid, info["url"], info.get("model", "default"),
                          info.get("version", "0"),
                          info.get("mode", "predict"),
                          identity=info.get("identity"),
                          pid=info.get("pid"), now=self._clock())
            rep.ready = bool(info.get("ready", False))
            rep.reason = info.get("reason")
            rep.load = dict(info.get("load") or {})
            rep.static = bool(info.get("static", False))
            rep.spec = dict(info.get("spec") or {})
            rep.layout = info.get("layout")
            self._replicas[rid] = rep
            self._notify("register", rep.to_info())
        self._publish_count()
        return rep

    def restore(self, infos):
        """Rebuild the table from journal-replayed ``to_info()`` dicts
        WITHOUT emitting mutations (replay must not re-journal itself).
        Restored replicas get a fresh heartbeat stamp: live ones beat
        again within MXNET_FLEET_HEARTBEAT_S, ones that died with the
        old router age out through the normal sweep."""
        now = self._clock()
        with self._lock:
            for info in infos:
                rep = Replica(info["id"], info["url"],
                              info.get("model", "default"),
                              info.get("version", "0"),
                              info.get("mode", "predict"),
                              identity=info.get("identity"),
                              pid=info.get("pid"), now=now)
                rep.ready = bool(info.get("ready", False))
                rep.reason = info.get("reason")
                rep.load = dict(info.get("load") or {})
                rep.static = bool(info.get("static", False))
                rep.spec = dict(info.get("spec") or {})
                rep.layout = info.get("layout")
                rep.draining = bool(info.get("draining", False))
                rep.dead = bool(info.get("dead", False))
                rep.dead_reason = info.get("dead_reason")
                self._replicas[rep.id] = rep
        self._publish_count()

    def heartbeat(self, rid, ready=None, reason=None, load=None):
        """Refresh liveness + readiness; returns False for an unknown id
        (the announcer re-registers on that — the router may have
        restarted and lost its table)."""
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is None:
                return False
            rep.last_heartbeat = self._clock()
            was = (rep.dead, rep.ready)
            if rep.dead:
                # a heartbeat from the "dead" is a liveness correction
                # (e.g. a transient proxy failure marked it dead)
                rep.dead = False
                rep.dead_reason = None
            if ready is not None:
                rep.ready = bool(ready)
            if reason is not None or ready:
                rep.reason = reason
            if load is not None:
                rep.load = dict(load)
            flipped = (rep.dead, rep.ready) != was
            if flipped:
                # journal readiness FLIPS, not every beat: load updates
                # are re-announced within a heartbeat interval anyway
                self._notify("state", {
                    "id": rep.id, "ready": rep.ready,
                    "reason": rep.reason, "dead": rep.dead,
                    "dead_reason": rep.dead_reason})
        if flipped:
            self._publish_count()
        return True

    def deregister(self, rid):
        with self._lock:
            gone = self._replicas.pop(str(rid), None) is not None
            if gone:
                self._notify("deregister", {"id": str(rid)})
        if gone:
            self._publish_count()
        return gone

    # -- router-driven state -----------------------------------------------
    def mark_dead(self, rid, why):
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is not None and not rep.dead:
                rep.dead = True
                rep.dead_reason = str(why)
                rep.ready = False
                self._notify("state", {
                    "id": rep.id, "ready": False, "dead": True,
                    "dead_reason": rep.dead_reason})
        self._publish_count()

    def mark_not_ready(self, rid, why):
        """Soft pull (a 503 from the data path): out of rotation until
        its next heartbeat says otherwise."""
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is not None:
                rep.ready = False
                rep.reason = str(why)
                self._notify("state", {
                    "id": rep.id, "ready": False, "reason": rep.reason})

    def set_draining(self, rid, draining=True):
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is None:
                return False
            rep.draining = bool(draining)
            self._notify("state", {"id": rep.id,
                                   "draining": rep.draining})
        self._publish_count()
        return True

    def note_inflight(self, rid, delta):
        with self._lock:
            rep = self._replicas.get(str(rid))
            if rep is not None:
                rep.inflight = max(0, rep.inflight + delta)
                if delta > 0:
                    rep.served += 1

    def sweep(self, now=None):
        """Mark replicas with stale heartbeats dead; returns the newly
        dead ids. Called lazily from every routing decision — no
        background thread needed. Staleness is measured on the
        registry's monotonic clock end to end (heartbeat stamps AND
        ``now``), so a wall-clock/NTP step can neither expire a healthy
        fleet nor keep a dead one alive."""
        now = self._clock() if now is None else now
        newly = []
        with self._lock:
            for rep in self._replicas.values():
                if (not rep.dead and not rep.static
                        and now - rep.last_heartbeat
                        > self.heartbeat_timeout_s):
                    rep.dead = True
                    rep.ready = False
                    rep.dead_reason = ("no heartbeat for %.1fs (timeout "
                                       "%.1fs)" % (now - rep.last_heartbeat,
                                                   self.heartbeat_timeout_s))
                    newly.append(rep.id)
                    self._notify("state", {
                        "id": rep.id, "ready": False, "dead": True,
                        "dead_reason": rep.dead_reason})
        if newly:
            self._publish_count()
        return newly

    # -- queries ------------------------------------------------------------
    def get(self, rid):
        with self._lock:
            return self._replicas.get(str(rid))

    def replicas(self):
        with self._lock:
            return list(self._replicas.values())

    def live_replicas(self):
        return [r for r in self.replicas() if not r.dead]

    def is_routable(self, rid):
        rep = self.get(rid)
        return (rep is not None and not rep.dead and not rep.draining
                and rep.ready)

    def routable(self, model=None, mode=None, version=None):
        """Replicas eligible for new traffic: alive, fresh heartbeat,
        reporting ready, not router-drained — filtered by model/mode/
        version when given."""
        self.sweep()
        out = []
        with self._lock:
            for rep in self._replicas.values():
                if rep.dead or rep.draining or not rep.ready:
                    continue
                if model is not None and rep.model != str(model):
                    continue
                if mode is not None and rep.mode != mode:
                    continue
                if version is not None and rep.version != str(version):
                    continue
                out.append(rep)
        return out

    def models(self):
        """{model: {version: [replica ids]}} over non-dead replicas."""
        out = {}
        with self._lock:
            for rep in self._replicas.values():
                if rep.dead:
                    continue
                out.setdefault(rep.model, {}).setdefault(
                    rep.version, []).append(rep.id)
        return out

    def snapshot(self):
        now = self._clock()
        with self._lock:
            reps = [r.snapshot(now) for r in self._replicas.values()]
        reps.sort(key=lambda r: r["id"])
        return {
            "replicas": reps,
            "counts": {
                "total": len(reps),
                "ready": sum(1 for r in reps
                             if r["ready"] and not r["dead"]
                             and not r["draining"]),
                "dead": sum(1 for r in reps if r["dead"]),
                "draining": sum(1 for r in reps if r["draining"]),
            },
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
        }


def _post_json(url, payload, timeout_s=3.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode() or "{}")


class ReplicaAnnouncer:
    """Replica-side registration + heartbeat client.

    ``info`` is the static registration payload (id/url/model/version/
    mode/identity/pid); ``status_fn()`` returns the live part each beat:
    ``{"ready": bool, "reason": str|None, "load": {...}}``. Failures are
    absorbed (a router restart must not kill a healthy replica); an
    unknown-id heartbeat answer triggers re-registration. Transient
    connection failures (refused/reset while a router restarts or
    fails over) retry on the shared ``supervisor.backoff_delay``
    jittered schedule — fast first retries so a replica rejoins the
    promoted router well inside one heartbeat interval, capped at the
    interval so a long outage costs no extra traffic.

    **Epoch fencing** (router HA): register/heartbeat replies carry the
    router's fencing epoch; the announcer feeds it to
    :mod:`mxnet_tpu.fleet.fencing`. A revived stale primary answering
    "unknown id, re-register" with an epoch below the highest ever
    observed is *refused* — this replica belongs to the promoted
    router's fleet now, and adopting the zombie would split-brain the
    registry (``stale_router_rejections`` counts the refusals)."""

    def __init__(self, router_url, info, status_fn, interval_s=None):
        if interval_s is None:
            from ..config import flags
            interval_s = flags.fleet_heartbeat_s
        self.router_url = str(router_url).rstrip("/")
        self.info = dict(info)
        self.status_fn = status_fn
        self.interval_s = float(interval_s)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self.registered = threading.Event()
        self.stale_router_rejections = 0
        self.conn_failures = 0       # consecutive, drives the backoff

    def _observe_epoch(self, out):
        """Feed a reply's epoch to the fence; False = stale router."""
        epoch = out.get("epoch")
        if epoch is None:
            return True
        from . import fencing
        if fencing.observe(epoch):
            return True
        self.stale_router_rejections += 1
        return False

    def _register_once(self):
        payload = dict(self.info)
        payload.update(self.status_fn())
        out = _post_json(self.router_url + "/fleet/register", payload)
        self._observe_epoch(out)
        self.registered.set()

    def _beat_once(self):
        status = self.status_fn()
        out = _post_json(self.router_url + "/fleet/heartbeat",
                         {"id": self.info["id"], **status})
        current = self._observe_epoch(out)
        if not out.get("known", True) and current:
            self._register_once()

    def _loop(self):
        from .supervisor import backoff_delay
        while not self._stop.is_set():
            wait = self.interval_s
            try:
                if not self.registered.is_set():
                    self._register_once()
                else:
                    self._beat_once()
                self.conn_failures = 0
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError):
                # router down/restarting/failing over: don't give up —
                # retry on the shared jittered restart schedule, fast
                # at first (rejoin a promoted router inside one beat),
                # capped at the heartbeat interval. The *stale-epoch*
                # refusal is deliberate and NOT retried here: it lives
                # in _observe_epoch, which simply never re-registers
                # with a demoted router.
                self.conn_failures += 1
                wait = backoff_delay(self.conn_failures - 1,
                                     base=min(0.05, self.interval_s),
                                     cap=self.interval_s)
            self._wake.wait(wait)
            self._wake.clear()

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="mxtpu-fleet-announcer",
                daemon=True)
            self._thread.start()
        return self

    def notify(self):
        """Force an immediate heartbeat (readiness just changed — e.g.
        drain began; the router should pull us from rotation *now*, not
        an interval later)."""
        self._wake.set()

    def stop(self, deregister=True):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(2.0)
        if deregister:
            try:
                _post_json(self.router_url + "/fleet/deregister",
                           {"id": self.info["id"]}, timeout_s=2.0)
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError):
                pass
