"""Supervised replica processes: the serving twin of ``tools/launch.py``.

The elastic-training launcher grew the restart discipline first —
capped jittered exponential backoff, a clean environment for restarted
incarnations (``MXNET_FAULT_INJECT`` cleared so an injected kill is a
first-run event), and postmortem-friendly death reporting. This module
extracts that discipline so serving replicas get the exact same
kill/resume treatment training workers do, and ``tools/launch.py``
imports :func:`backoff_delay` from here (by file path, so the launcher
keeps its no-library-imports property) instead of keeping a private
copy.

Deliberately **stdlib-only and import-light**: the supervisor runs in
the router/operator process, which must never pay a jax import (or pull
device state into a process that only fork/execs children).

    sup = ReplicaSupervisor()
    sup.add(ReplicaSpec("r0", [sys.executable, "tools/serve.py", ...]))
    sup.poll()          # reap deaths, launch due restarts; returns events
    sup.stop()          # SIGTERM everything (graceful replica drain)

The supervisor is *policy-free about readiness*: it keeps processes
alive; the fleet registry (heartbeats) decides when a replica is
routable. Death of a child is an **event**, not an exception — the
router keeps serving the survivors while the supervisor backs off and
restarts (ROADMAP item 1's ~1/N degradation story).
"""
from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time

__all__ = ["backoff_delay", "ReplicaSpec", "ReplicaSupervisor"]


def backoff_delay(attempt, base=1.0, cap=30.0, jitter=0.5, rng=None):
    """Capped jittered exponential backoff delay for restart ``attempt``
    (0-based): ``min(cap, base * 2**attempt)`` scaled by a uniform
    ``[1-jitter, 1+jitter]`` factor. The one restart schedule shared by
    the training launcher and the serving fleet supervisor — jitter
    de-synchronizes mass restarts, the cap bounds recovery latency."""
    rng = rng if rng is not None else random
    base = max(0.0, float(base))
    raw = min(float(cap), base * (2.0 ** int(attempt)))
    return raw * rng.uniform(1.0 - jitter, 1.0 + jitter)


class ReplicaSpec:
    """How to (re)launch one supervised child process.

    ``argv`` is the full command line. ``env`` overlays ``os.environ``.
    ``max_restarts`` bounds supervised restarts (0 = never restart —
    fault-drill victims stay down so degraded goodput is observable).
    Restarted incarnations get ``MXNET_FAULT_INJECT`` cleared (same
    contract as tools/launch.py) and ``MXNET_REPLICA_INCARNATION`` set,
    so an injected death never re-fires on the replacement."""

    def __init__(self, replica_id, argv, env=None, cwd=None,
                 max_restarts=2, log_path=None):
        self.replica_id = str(replica_id)
        self.argv = list(argv)
        self.env = dict(env or {})
        self.cwd = cwd
        self.max_restarts = int(max_restarts)
        self.log_path = log_path


class _Child:
    __slots__ = ("spec", "proc", "incarnation", "state", "rc",
                 "restart_at", "started_at", "log_file")

    def __init__(self, spec):
        self.spec = spec
        self.proc = None
        self.incarnation = 0      # how many times spawned
        self.state = "new"        # new|running|backoff|failed|stopped
        self.rc = None
        self.restart_at = None
        self.started_at = None
        self.log_file = None


class ReplicaSupervisor:
    """Keeps a set of :class:`ReplicaSpec` children running.

    Synchronous by design: callers drive :meth:`poll` (tests step it
    deterministically) or run :meth:`start` for a background poller
    thread. ``on_event`` (optional callable) receives each event dict
    as it happens; :meth:`poll` also returns the batch."""

    def __init__(self, backoff_base=1.0, backoff_cap=30.0, rng=None,
                 on_event=None):
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._rng = rng if rng is not None else random
        self._on_event = on_event
        self._children = {}
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()

    # -- membership ---------------------------------------------------------
    def add(self, spec, start=True):
        """Register (and by default immediately launch) one replica."""
        with self._lock:
            if spec.replica_id in self._children:
                raise ValueError("supervisor: duplicate replica id %r"
                                 % spec.replica_id)
            child = _Child(spec)
            self._children[spec.replica_id] = child
        if start:
            self._spawn(child)
        return self

    def _spawn(self, child):
        spec = child.spec
        env = dict(os.environ)
        env.update(spec.env)
        if child.incarnation > 0:
            # restarted incarnation runs clean: the injected fault that
            # killed incarnation N must not kill N+1 (launch.py contract)
            env["MXNET_FAULT_INJECT"] = ""
        env["MXNET_REPLICA_INCARNATION"] = str(child.incarnation)
        stdout = stderr = None
        if spec.log_path:
            child.log_file = open(spec.log_path, "ab", buffering=0)
            stdout = stderr = child.log_file
        child.proc = subprocess.Popen(spec.argv, env=env, cwd=spec.cwd,
                                      stdout=stdout, stderr=stderr)
        child.incarnation += 1
        child.state = "running"
        child.rc = None
        child.restart_at = None
        child.started_at = time.monotonic()

    # -- polling ------------------------------------------------------------
    def _emit(self, events, **ev):
        events.append(ev)
        if self._on_event is not None:
            try:
                self._on_event(ev)
            except Exception:
                pass

    def poll(self):
        """One supervision round: reap dead children, schedule restarts
        with backoff, launch restarts whose delay elapsed. Returns the
        list of event dicts (``exit``/``restart_scheduled``/
        ``restart``/``failed``)."""
        events = []
        now = time.monotonic()
        with self._lock:
            children = list(self._children.values())
        for child in children:
            if child.state == "running":
                rc = child.proc.poll()
                if rc is None:
                    continue
                child.rc = rc
                restarts_used = child.incarnation - 1
                self._emit(events, event="exit",
                           replica=child.spec.replica_id, rc=rc,
                           incarnation=child.incarnation - 1)
                if restarts_used < child.spec.max_restarts:
                    delay = backoff_delay(restarts_used,
                                          base=self.backoff_base,
                                          cap=self.backoff_cap,
                                          rng=self._rng)
                    child.restart_at = now + delay
                    child.state = "backoff"
                    self._emit(events, event="restart_scheduled",
                               replica=child.spec.replica_id,
                               delay_s=round(delay, 3),
                               attempt=restarts_used)
                else:
                    child.state = "failed"
                    self._emit(events, event="failed",
                               replica=child.spec.replica_id, rc=rc,
                               restarts=restarts_used)
            if child.state == "backoff" and now >= child.restart_at:
                self._spawn(child)
                self._emit(events, event="restart",
                           replica=child.spec.replica_id,
                           incarnation=child.incarnation - 1)
        return events

    def run(self, duration_s, interval_s=0.2):
        """Poll for ``duration_s`` seconds (drill convenience)."""
        t_end = time.monotonic() + duration_s
        events = []
        while time.monotonic() < t_end and not self._stop.is_set():
            events.extend(self.poll())
            time.sleep(interval_s)
        return events

    def start(self, interval_s=0.2):
        """Background poller thread (daemon); :meth:`stop` ends it."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self.run, args=(float("inf"), interval_s),
                name="mxtpu-fleet-supervisor", daemon=True)
            self._thread.start()
        return self

    def kill(self, replica_id, sig=signal.SIGKILL):
        """Fault-drill helper: signal a child WITHOUT marking it
        stopped, so :meth:`poll` observes the death as an event and
        (budget permitting) restarts it — exactly what an external
        kill looks like. Returns the signalled pid or None."""
        with self._lock:
            child = self._children[replica_id]
        if child.proc is not None and child.proc.poll() is None:
            try:
                child.proc.send_signal(sig)
                return child.proc.pid
            except OSError:
                pass
        return None

    # -- shutdown -----------------------------------------------------------
    def stop(self, replica_id=None, sig=signal.SIGTERM, wait_s=10.0):
        """Signal children (default SIGTERM — replicas drain gracefully)
        and wait for exit; SIGKILL anything that overstays ``wait_s``.
        ``replica_id=None`` stops every child and the poller thread."""
        # snapshot under the lock (the poller mutates _children while
        # it restarts children), signal/wait outside it
        with self._lock:
            if replica_id is None:
                self._stop.set()
                targets = list(self._children.values())
            else:
                targets = [self._children[replica_id]]
        for child in targets:
            child.state = "stopped"     # poll() must not restart it
            if child.proc is not None and child.proc.poll() is None:
                try:
                    child.proc.send_signal(sig)
                except OSError:
                    pass
        deadline = time.monotonic() + wait_s
        for child in targets:
            if child.proc is None:
                continue
            budget = max(0.0, deadline - time.monotonic())
            try:
                child.proc.wait(budget)
            except subprocess.TimeoutExpired:
                child.proc.kill()
                child.proc.wait(5.0)
            if child.log_file is not None:
                try:
                    child.log_file.close()
                except OSError:
                    pass
                child.log_file = None
        if replica_id is None and self._thread is not None:
            self._thread.join(wait_s)

    # -- observability ------------------------------------------------------
    def statuses(self):
        """JSON-able per-replica supervision state."""
        out = {}
        with self._lock:
            for rid, c in self._children.items():
                out[rid] = {
                    "state": c.state,
                    "pid": c.proc.pid if c.proc is not None else None,
                    "incarnation": max(0, c.incarnation - 1),
                    "rc": c.rc,
                    "max_restarts": c.spec.max_restarts,
                }
        return out

    def alive_count(self):
        with self._lock:
            children = list(self._children.values())
        return sum(1 for c in children
                   if c.state == "running" and c.proc.poll() is None)


if __name__ == "__main__":     # tiny smoke: supervise `sleep`, kill it
    sup = ReplicaSupervisor(backoff_base=0.1)
    sup.add(ReplicaSpec("demo", [sys.executable, "-c",
                                 "import time; time.sleep(60)"],
                        max_restarts=1))
    sup._children["demo"].proc.kill()
    time.sleep(0.2)
    print(sup.poll())
    sup.stop()
