"""Cross-host WAL replication: stream the fleet journal to standbys.

PR-14's router HA made failover bitwise — but only if the journal
directory survives the primary, because the write-ahead log lived on
exactly one disk (ROADMAP item 2's residual: "the WAL assumes shared
or surviving storage"). This module closes that gap with a
**pull-based replication tier**: a standby runs a
:class:`JournalReplicator` that streams the primary's journal over the
router's own HTTP front end into a *local* replica directory, so
``tools/route.py --standby --replicate-from URL`` promotes from its
own copy of the log even when the primary's machine (and disk) die
together.

Design points, in the order a cold standby meets them:

* **Snapshot bootstrap.** The manifest (``GET /journal/manifest``)
  names the newest compaction snapshot; a cold standby downloads it
  first so it starts O(snapshot) behind, not O(history).
* **Offset-resumed segment fetches.** Each poll fetches only the
  bytes past the local copy's size (``GET /journal/segment?name=..&
  offset=N``); a restarted standby re-verifies its local files and
  resumes from where it left off.
* **CRC re-verified on the receiving side.** Fetched bytes are
  *appended then proven*: :func:`journal.read_segment` re-walks the
  CRC32 framing locally, and anything past the last whole record —
  an in-transit bit flip, a fetch that raced the primary mid-write —
  is truncated off and re-fetched, never applied.
* **Seq-gap detection with automatic full re-sync.** Records apply in
  sequence; a gap (``seq > applied_seq + 1``) or a history regression
  (source seq behind the replica's) means the local replica cannot be
  patched record-by-record, so it is wiped and re-bootstrapped from
  the source's snapshot + segments in the same poll.
* **Epoch-stamped responses.** The manifest carries the serving
  router's fencing epoch and every segment/snapshot response carries
  ``X-Fleet-Epoch``; the replicator tracks the highest epoch it has
  ever observed and refuses anything older — a demoted primary can
  never feed a promoted standby (:class:`StaleSourceError`, counted
  in ``fleet/repl_stale_rejects``).
* **Jittered retry/backoff.** Transient connection failures back off
  on the shared ``supervisor.backoff_delay`` schedule (the same one
  the launcher, supervisor, and announcer use); a healthy catch-up
  polls with zero delay (burst) and an idle replica decays to the
  ``MXNET_FLEET_REPL_POLL_S`` cap.

Liveness rides the same channel: the manifest embeds the primary's
lease beat, so :meth:`JournalReplicator.expired` measures *monotonic
time since the manifest content last changed* — the replicating
standby needs no shared lease file, mirroring ``LeaseMonitor``'s
NTP-proof content-change discipline.

Observability: ``fleet/repl_lag_records`` (source seq minus replica
seq — the headline gauge the disk-loss drill asserts in federated
/metrics), ``fleet/repl_seq``, ``fleet/repl_bytes``,
``fleet/repl_fetches``, ``fleet/repl_fetch_errors``,
``fleet/repl_crc_rejects``, ``fleet/repl_stale_rejects``,
``fleet/repl_resyncs``, ``fleet/repl_snapshots``.
"""
from __future__ import annotations

import json
import os
import re
import time
import urllib.error
import urllib.parse
import urllib.request

from ..base import MXNetError
from ..checkpoint import atomic_replace
from .. import telemetry
from .journal import (FleetState, read_lease, read_segment, _segments,
                      _snapshots)
from .supervisor import backoff_delay

__all__ = ["JournalReplicator", "ReplicationError", "StaleSourceError",
           "build_manifest", "read_journal_file"]

# one fetch is bounded so a huge segment can't balloon either side's
# memory; the replicator's catch-up burst (zero-delay re-poll) drains
# the rest immediately
MAX_FETCH_BYTES = 8 << 20

_NAME_RE = re.compile(r"^(wal-\d{8}\.log|snap-\d{16}\.json)$")

EPOCH_HEADER = "X-Fleet-Epoch"


class ReplicationError(MXNetError):
    """Journal replication failed in a way retrying won't fix."""


class StaleSourceError(ReplicationError):
    """The source answered with a fencing epoch below the highest this
    replicator has ever observed: it is a demoted primary and must not
    feed us (its history may have diverged from the promoted one)."""


# ---------------------------------------------------------------------------
# primary side: manifest + bounded file reads (served by the router's
# HTTP front end — fleet/router.py wires /journal/* to these)
# ---------------------------------------------------------------------------

def build_manifest(jdir, epoch, seq):
    """The primary's replication manifest: fencing epoch, current seq,
    live segments with sizes, the newest snapshot, and the lease beat
    (the liveness signal, so replicating standbys need no shared lease
    file)."""
    segs = [{"name": os.path.basename(p), "size": os.path.getsize(p)}
            for _, p in _segments(jdir) if os.path.exists(p)]
    snap = None
    snaps = _snapshots(jdir)
    if snaps:
        n, p = snaps[-1]
        try:
            snap = {"name": os.path.basename(p), "seq": int(n),
                    "size": os.path.getsize(p)}
        except OSError:
            snap = None
    lease, _ = read_lease(jdir)
    return {"epoch": int(epoch or 0), "seq": int(seq or 0),
            "segments": segs, "snapshot": snap,
            "beat": (lease or {}).get("beat")}


def read_journal_file(jdir, name, offset=0, max_bytes=MAX_FETCH_BYTES):
    """Bounded read of one journal file for a replication fetch.
    ``name`` must be a bare ``wal-*.log`` / ``snap-*.json`` basename
    (no path traversal). Raises ``KeyError`` for anything else or a
    missing file."""
    if not _NAME_RE.match(name or ""):
        raise KeyError("not a journal file: %r" % (name,))
    path = os.path.join(os.fspath(jdir), name)
    try:
        with open(path, "rb") as f:
            f.seek(max(0, int(offset)))
            return f.read(max(0, int(max_bytes)))
    except OSError:
        raise KeyError("no such journal file: %r" % (name,))


# ---------------------------------------------------------------------------
# standby side
# ---------------------------------------------------------------------------

class JournalReplicator:
    """Pulls a primary's journal into a local replica directory.

    ``poll()`` runs one replication round (manifest, snapshot,
    segment tails, verify, apply) and never raises on transient
    failure — it counts the failure and lets :meth:`next_delay_s`
    back off. The local directory is a valid journal directory at all
    times: ``Router.from_journal(dir)`` on it is exactly the
    promotion path, which is the whole point."""

    def __init__(self, source_url, dir_, poll_s=None, timeout_s=None,
                 retry_base=0.05, retry_cap=None, rng=None):
        from ..config import flags
        self.source_url = str(source_url).rstrip("/")
        self.dir = os.fspath(dir_)
        os.makedirs(self.dir, exist_ok=True)
        self.poll_s = (flags.fleet_repl_poll_s if poll_s is None
                       else float(poll_s))
        self.timeout_s = (flags.fleet_repl_timeout_s if timeout_s is None
                          else float(timeout_s))
        self.retry_base = float(retry_base)
        self.retry_cap = (max(4 * self.poll_s, 0.5) if retry_cap is None
                          else float(retry_cap))
        self._rng = rng
        self.state = FleetState()
        self._offsets = {}           # basename -> verified byte offset
        self.max_epoch = 0
        self.source_seq = 0
        self.conn_failures = 0       # consecutive, drives the backoff
        self._last_applied = 0
        self._last_content = None
        self._changed_at = time.monotonic()
        reg = telemetry.default_registry()
        self._g_lag = reg.gauge(
            "fleet/repl_lag_records",
            "Journal replication lag: source seq minus the replica's "
            "applied seq.")
        self._g_seq = reg.gauge(
            "fleet/repl_seq", "Highest journal seq applied by this "
            "replicating standby.")
        self._c_bytes = reg.counter(
            "fleet/repl_bytes", "Journal bytes streamed from the "
            "replication source.")
        self._c_fetches = reg.counter(
            "fleet/repl_fetches", "Replication HTTP fetches "
            "(manifest/segment/snapshot).")
        self._c_fetch_errors = reg.counter(
            "fleet/repl_fetch_errors", "Transient replication fetch "
            "failures (retried with jittered backoff).")
        self._c_crc_rejects = reg.counter(
            "fleet/repl_crc_rejects", "Fetched segment bytes dropped "
            "by the receiver-side CRC re-verification (truncated and "
            "re-fetched, never applied).")
        self._c_stale_rejects = reg.counter(
            "fleet/repl_stale_rejects", "Replication responses refused "
            "because the source's fencing epoch was below the highest "
            "observed (demoted primary).")
        self._c_resyncs = reg.counter(
            "fleet/repl_resyncs", "Full re-syncs after a seq gap or "
            "history regression (local replica wiped and "
            "re-bootstrapped).")
        self._c_snapshots = reg.counter(
            "fleet/repl_snapshots", "Snapshot bootstraps/adoptions "
            "fetched from the source.")
        self._bootstrap_local()

    # -- local resume -------------------------------------------------------
    def _bootstrap_local(self):
        """Re-verify whatever a previous incarnation already fetched:
        adopt the newest local snapshot, walk every local segment's CRC
        framing to rebuild verified offsets, truncate any unverified
        tail (it will be re-fetched). This is what makes segment
        fetches offset-*resumed* across standby restarts."""
        for _snap_seq, path in reversed(_snapshots(self.dir)):
            try:
                with open(path) as f:
                    self.state = FleetState.from_dict(json.load(f))
                break
            except (OSError, ValueError, KeyError, TypeError):
                continue
        for _, path in _segments(self.dir):
            records, off, clean = read_segment(path, 0)
            for seq, kind, data in records:
                self.state.apply(seq, kind, data)
            self._offsets[os.path.basename(path)] = off
            if not clean:
                self._truncate(path, off)
        self.max_epoch = self.state.epoch
        self._g_seq.set(self.state.applied_seq)

    @staticmethod
    def _truncate(path, size):
        try:
            with open(path, "r+b") as f:
                f.truncate(max(0, int(size)))
        except OSError:
            pass

    # -- fetch plumbing -----------------------------------------------------
    def _check_epoch(self, epoch):
        if epoch is None:
            return
        epoch = int(epoch)
        if epoch < self.max_epoch:
            self._c_stale_rejects.inc()
            raise StaleSourceError(
                "replication source %s serves epoch %d but epoch %d "
                "was already observed — demoted primary refused"
                % (self.source_url, epoch, self.max_epoch))
        self.max_epoch = epoch

    def _get(self, path):
        req = urllib.request.Request(self.source_url + path)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            data = r.read()
            headers = dict(r.headers)
        self._c_fetches.inc()
        self._check_epoch(headers.get(EPOCH_HEADER))
        return data

    def _fetch_manifest(self):
        man = json.loads(self._get("/journal/manifest").decode("utf-8"))
        self._check_epoch(man.get("epoch"))
        return man

    def _fetch_file(self, kind, name, offset=0):
        q = urllib.parse.urlencode({"name": name, "offset": int(offset)})
        return self._get("/journal/%s?%s" % (kind, q))

    # -- liveness (the standby's promotion trigger) -------------------------
    def age_s(self):
        """Monotonic seconds since the manifest content (epoch, seq,
        lease beat) last changed — the replicating standby's analogue
        of ``LeaseMonitor.age_s``. Fetch failures leave the clock
        running, so a dead source ages out naturally."""
        return time.monotonic() - self._changed_at

    def expired(self, timeout_s):
        return self.age_s() > float(timeout_s)

    # -- the pull loop ------------------------------------------------------
    def poll(self):
        """One replication round; returns records applied. Transient
        connection failures and stale-source refusals are absorbed
        (counted; :meth:`next_delay_s` backs off / :meth:`expired`
        eventually promotes)."""
        applied = 0
        try:
            man = self._fetch_manifest()
            self.conn_failures = 0
            content = (man.get("epoch"), man.get("seq"), man.get("beat"))
            if content != self._last_content:
                self._last_content = content
                self._changed_at = time.monotonic()
            self.source_seq = int(man.get("seq") or 0)
            applied = self._sync_once(man, allow_resync=True)
        except StaleSourceError:
            pass          # never apply; age_s() keeps growing
        except (urllib.error.URLError, ConnectionError, OSError,
                ValueError, KeyError) as e:
            self.conn_failures += 1
            self._c_fetch_errors.inc()
            self._last_error = str(e)
        self._last_applied = applied
        self._g_lag.set(max(0, self.source_seq - self.state.applied_seq))
        self._g_seq.set(self.state.applied_seq)
        return applied

    def next_delay_s(self):
        """Pace for the caller's loop: jittered exponential backoff
        while the source is unreachable, zero right after progress
        (catch-up burst), the poll interval when idle and healthy."""
        if self.conn_failures:
            return min(self.retry_cap,
                       backoff_delay(self.conn_failures - 1,
                                     base=self.retry_base,
                                     cap=self.retry_cap, rng=self._rng))
        if self._last_applied:
            return 0.0
        return self.poll_s

    def _resync(self):
        """Wipe the local replica and start over: a seq gap or history
        regression means record-by-record patching cannot reconverge
        (the missing prefix is gone from the source's segments)."""
        self._c_resyncs.inc()
        for _, p in _segments(self.dir) + _snapshots(self.dir):
            try:
                os.unlink(p)
            except OSError:
                pass
        self._offsets.clear()
        self.state = FleetState()

    def _adopt_snapshot(self, snap):
        """Fetch/refresh the source's newest snapshot locally, adopt it
        when it is ahead of the replica state. Returns True if the
        local file is present and loadable (gates segment GC)."""
        name = snap["name"]
        path = os.path.join(self.dir, name)
        want = int(snap.get("size") or 0)
        have = os.path.getsize(path) if os.path.exists(path) else -1
        if have != want:
            data = self._fetch_file("snapshot", name)
            state = FleetState.from_dict(json.loads(data.decode("utf-8")))
            with atomic_replace(path) as tmp:
                with open(tmp, "wb") as f:
                    f.write(data)
            self._c_bytes.inc(len(data))
            self._c_snapshots.inc()
        else:
            with open(path) as f:
                state = FleetState.from_dict(json.load(f))
        if state.applied_seq > self.state.applied_seq:
            self.state = state
        return True

    def _sync_once(self, man, allow_resync):
        applied = 0
        if allow_resync and 0 < self.source_seq < self.state.applied_seq:
            # the source's history is BEHIND us at the same (or newer)
            # epoch: it restarted with a fresh journal — ours is a
            # different history now
            self._resync()
            return self._sync_once(man, allow_resync=False)
        snap_ok = False
        snap = man.get("snapshot")
        if snap and _NAME_RE.match(str(snap.get("name") or "")):
            try:
                snap_ok = self._adopt_snapshot(snap)
            except (ValueError, KeyError, TypeError, OSError):
                snap_ok = False   # half-written on the source; retry
        remote = {}
        for seg in man.get("segments") or []:
            name = str(seg.get("name") or "")
            if _NAME_RE.match(name):
                remote[name] = int(seg.get("size") or 0)
        for name, want in sorted(remote.items()):
            path = os.path.join(self.dir, name)
            have = os.path.getsize(path) if os.path.exists(path) else 0
            if have < want:
                data = self._fetch_file("segment", name, offset=have)
                if data:
                    with open(path, "ab") as f:
                        f.write(data)
                    self._c_bytes.inc(len(data))
            # receiver-side CRC re-verification: only whole, checksummed
            # records past the verified offset are applied
            off = self._offsets.get(name, 0)
            records, new_off, clean = read_segment(path, off)
            gap = False
            for seq, kind, data_ in records:
                # a first record past seq 1 on a cold replica is a gap
                # too: starting mid-history would silently drop the
                # prefix (the snapshot bootstrap is the only legal way
                # to skip ahead)
                if seq > self.state.applied_seq + 1:
                    gap = True
                    break
                if self.state.apply(seq, kind, data_):
                    applied += 1
            if gap:
                if allow_resync:
                    self._resync()
                    return applied + self._sync_once(
                        man, allow_resync=False)
                break     # gap persists post-resync: wait for a snapshot
            self._offsets[name] = new_off
            if not clean:
                # garbage past the last whole record — an in-transit
                # flip or a fetch racing the primary mid-write: drop it
                # so the next poll re-fetches from the good offset
                size_now = (os.path.getsize(path)
                            if os.path.exists(path) else 0)
                if size_now > new_off:
                    self._truncate(path, new_off)
                    self._c_crc_rejects.inc()
        # mirror the source's retention: segments it compacted away are
        # deleted locally, but only once the covering snapshot is local
        # (promotion replays this directory; never orphan the prefix)
        if snap_ok:
            for _, p in _segments(self.dir):
                name = os.path.basename(p)
                if name not in remote:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                    self._offsets.pop(name, None)
            for _, p in _snapshots(self.dir):
                if os.path.basename(p) != snap["name"]:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
        return applied

    def stats(self):
        return {
            "source": self.source_url,
            "dir": self.dir,
            "applied_seq": self.state.applied_seq,
            "source_seq": self.source_seq,
            "lag_records": max(0,
                               self.source_seq - self.state.applied_seq),
            "max_epoch": self.max_epoch,
            "conn_failures": self.conn_failures,
            "age_s": round(self.age_s(), 3),
        }
