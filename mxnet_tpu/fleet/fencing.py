"""Fencing epochs: split-brain protection for router failover.

Every router incarnation that owns the fleet journal gets a
monotonically increasing **epoch** (replayed-max + 1). The router
stamps it into registration/heartbeat replies and into every request
body it forwards (``"fleet_epoch"``); replicas track the highest epoch
they have ever observed here and *reject* anything below it with a
409. So when a SIGKILLed primary is revived while the standby already
promoted, the zombie's forwarded writes bounce off every replica and
its registration offers are ignored by the announcer — it can serve
stale answers to nobody.

Process-global on purpose: one serving process talks to one fleet, and
the fence must hold across every front-end thread. Stdlib-only.
"""
from __future__ import annotations

import threading

__all__ = ["observe", "current", "is_stale", "reset"]

_lock = threading.Lock()
_epoch = 0


def observe(epoch):
    """Record an observed epoch. Returns True when ``epoch`` is
    current-or-newer (and advances the fence), False when it is stale —
    the caller must reject the write that carried it."""
    global _epoch
    if epoch is None:
        return True         # pre-HA routers carry no epoch: not fenced
    e = int(epoch)
    with _lock:
        if e < _epoch:
            return False
        _epoch = e
        return True


def current():
    with _lock:
        return _epoch


def is_stale(epoch):
    return epoch is not None and int(epoch) < current()


def reset():
    """Test hook: forget the fence (a fresh process observes from 0)."""
    global _epoch
    with _lock:
        _epoch = 0
