"""Text utilities (parity: python/mxnet/contrib/text/)."""
from . import embedding
from . import utils
from . import vocab
from .vocab import Vocabulary
