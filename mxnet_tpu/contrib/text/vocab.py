"""Token indexing (parity: python/mxnet/contrib/text/vocab.py:30).

Index 0 is always the unknown token; reserved tokens follow; counter keys
are then indexed most-frequent-first (ties broken by token sort order),
subject to ``most_freq_count`` / ``min_freq``.
"""
import collections

UNKNOWN_IDX = 0


class Vocabulary:
    """Indexes unknown/reserved tokens plus the frequent keys of a Counter."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq <= 0:
            raise ValueError("`min_freq` must be positive")
        if reserved_tokens is not None:
            rset = set(reserved_tokens)
            if unknown_token in rset:
                raise ValueError("`reserved_tokens` cannot contain "
                                 "`unknown_token`")
            if len(rset) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` cannot contain duplicates")

        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._reserved_tokens = None
        if reserved_tokens is not None:
            self._reserved_tokens = list(reserved_tokens)
            self._idx_to_token.extend(reserved_tokens)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        if not isinstance(counter, collections.Counter):
            raise TypeError("`counter` must be a collections.Counter")
        special = set(self._idx_to_token)
        # frequency desc, then token order for stable ties
        ordered = sorted(counter.items(), key=lambda kv: kv[0])
        ordered.sort(key=lambda kv: kv[1], reverse=True)
        cap = len(special) + (len(counter) if most_freq_count is None
                              else most_freq_count)
        for token, freq in ordered:
            if freq < min_freq or len(self._idx_to_token) == cap:
                break
            if token not in special:
                self._idx_to_token.append(token)
                self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to UNKNOWN_IDX."""
        single = not isinstance(tokens, list)
        seq = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in seq]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Index/indices -> token(s); out-of-range raises ValueError."""
        single = not isinstance(indices, list)
        seq = [indices] if single else indices
        out = []
        for idx in seq:
            if not isinstance(idx, int) or not 0 <= idx < len(self._idx_to_token):
                raise ValueError("Token index %s is invalid" % (idx,))
            out.append(self._idx_to_token[idx])
        return out[0] if single else out
