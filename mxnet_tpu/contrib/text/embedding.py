"""Pre-trained token embeddings (parity: python/mxnet/contrib/text/
embedding.py:133-705 — _TokenEmbedding, GloVe, FastText, CustomEmbedding,
CompositeEmbedding, register/create).

The embedding matrix lives as an ``NDArray`` (device-resident jax array),
so ``get_vecs_by_tokens`` is a device gather and the matrix can seed a
``gluon.nn.Embedding`` weight directly.

Environment note: this build runs with zero egress, so GloVe/FastText do
not download; they load their standard-named files from ``embedding_root``
(default ``~/.mxnet/embeddings``) and raise with the expected path if the
file is absent.
"""
import io
import logging
import os
import warnings

import numpy as np

from . import vocab as _vocab
from .vocab import UNKNOWN_IDX
from ... import ndarray as nd
from ...base import MXNetError


class _Registry:
    def __init__(self):
        self.cls_by_name = {}


_REG = _Registry()


def register(embedding_cls):
    """Register a ``_TokenEmbedding`` subclass under its lowercase name."""
    name = embedding_cls.__name__.lower()
    _REG.cls_by_name[name] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding, e.g. ``create('glove', ...)``."""
    name = embedding_name.lower()
    if name not in _REG.cls_by_name:
        raise KeyError("unknown embedding %r; registered: %s"
                       % (embedding_name, sorted(_REG.cls_by_name)))
    return _REG.cls_by_name[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names, per embedding or as a full dict."""
    if embedding_name is not None:
        return list(_REG.cls_by_name[embedding_name.lower()]
                    .pretrained_file_name_sha1)
    return {name: list(cls.pretrained_file_name_sha1)
            for name, cls in _REG.cls_by_name.items()}


class _TokenEmbedding(_vocab.Vocabulary):
    """Base: a vocabulary plus an aligned ``idx_to_vec`` matrix."""

    pretrained_file_name_sha1 = {}

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = None
        self._idx_to_vec = None

    # -- loading -----------------------------------------------------------
    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        cls._check_pretrained_file_names(pretrained_file_name)
        embedding_root = os.path.expanduser(embedding_root)
        path = os.path.join(embedding_root, cls.__name__.lower(),
                            pretrained_file_name)
        if not os.path.isfile(path):
            raise MXNetError(
                "pretrained embedding file %s not found; downloads are "
                "disabled in this environment — place the file there "
                "manually" % path)
        return path

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if cls.pretrained_file_name_sha1 and \
                pretrained_file_name not in cls.pretrained_file_name_sha1:
            raise KeyError(
                "cannot find pretrained file %s for %s; valid: %s"
                % (pretrained_file_name, cls.__name__,
                   sorted(cls.pretrained_file_name_sha1)))

    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parse ``token<d>v1<d>v2...`` lines; first occurrence of a token
        wins; 1-element lines (headers) are skipped; index 0 is the unknown
        vector (loaded if present in the file, else ``init_unknown_vec``)."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError("`pretrained_file_path` must be a valid path "
                             "to the pre-trained token embedding file")
        logging.info("loading embedding vectors from %s", pretrained_file_path)

        # tokens indexed before loading (unknown + reserved + any counter
        # keys) each own a matrix row up front — file rows append after
        # them, so indices and rows stay aligned for every token
        n_pre = len(self._idx_to_token)
        vec_len = None
        rows = []
        pre_rows = {}   # pre-indexed token idx -> vector found in the file
        seen = set()
        loaded_unknown = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f, 1):
                elems = line.rstrip().split(elem_delim)
                if len(elems) <= 1:
                    raise MXNetError(
                        "line %d of %s: unexpected data format"
                        % (line_num, pretrained_file_path))
                token, vec = elems[0], [float(x) for x in elems[1:]]
                if token in seen:
                    warnings.warn("line %d: duplicate embedding for token %s "
                                  "skipped" % (line_num, token))
                    continue
                if token == self.unknown_token:
                    loaded_unknown = vec
                    seen.add(token)
                    continue
                if len(vec) == 1:
                    warnings.warn("line %d: token %s with 1-d vector is "
                                  "likely a header; skipped"
                                  % (line_num, token))
                    continue
                if vec_len is None:
                    vec_len = len(vec)
                elif len(vec) != vec_len:
                    raise MXNetError("line %d: vector dimension %d != %d"
                                     % (line_num, len(vec), vec_len))
                seen.add(token)
                if token in self._token_to_idx:   # reserved/pre-indexed
                    pre_rows[self._token_to_idx[token]] = vec
                else:
                    rows.append(vec)
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = len(self._idx_to_token) - 1

        if vec_len is None:
            raise MXNetError("no embedding vectors found in %s"
                             % pretrained_file_path)
        self._vec_len = vec_len
        mat = np.zeros((n_pre + len(rows), vec_len), np.float32)
        if rows:
            mat[n_pre:] = np.asarray(rows, np.float32)
        for idx, vec in pre_rows.items():
            mat[idx] = vec
        mat[UNKNOWN_IDX] = (np.asarray(loaded_unknown, np.float32)
                            if loaded_unknown is not None
                            else init_unknown_vec(shape=vec_len).asnumpy())
        self._idx_to_vec = nd.array(mat)

    def _build_embedding_for_vocabulary(self, vocabulary):
        """Re-index this embedding's vectors by ``vocabulary``'s indices
        (tokens absent from the source get the unknown vector)."""
        if vocabulary is None:
            return
        src_tok2idx = self._token_to_idx
        src_vecs = self._idx_to_vec.asnumpy()
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = (list(vocabulary.reserved_tokens)
                                 if vocabulary.reserved_tokens else None)
        sel = np.array([src_tok2idx.get(t, UNKNOWN_IDX)
                        for t in self._idx_to_token], np.int32)
        self._idx_to_vec = nd.array(src_vecs[sel])

    # -- lookups -----------------------------------------------------------
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        """(len(vocab), vec_len) NDArray aligned with ``idx_to_token``."""
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vector(s) for token(s); unknown tokens get the unknown vector.
        ``lower_case_backup`` retries a miss with the lowercased token."""
        single = not isinstance(tokens, list)
        seq = [tokens] if single else tokens
        if lower_case_backup:
            indices = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), UNKNOWN_IDX))
                for t in seq]
        else:
            indices = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in seq]
        vecs = self._idx_to_vec[nd.array(indices, dtype="int32")]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of known tokens (device-side scatter)."""
        if not isinstance(tokens, list):
            tokens = [tokens]
        if not isinstance(new_vectors, nd.NDArray):
            new_vectors = nd.array(new_vectors)
        if new_vectors.ndim == 1:
            new_vectors = new_vectors.reshape(1, -1)
        if len(tokens) != new_vectors.shape[0]:
            raise ValueError("`tokens` and `new_vectors` length mismatch")
        indices = []
        for t in tokens:
            if t not in self._token_to_idx:
                raise ValueError(
                    "token %r is unknown; to update the unknown-token vector "
                    "use unknown_token explicitly" % (t,))
            indices.append(self._token_to_idx[t])
        self._idx_to_vec[nd.array(indices, dtype="int32")] = new_vectors


@register
class GloVe(_TokenEmbedding):
    """GloVe vectors (nlp.stanford.edu/projects/glove); loads the standard
    txt file from ``embedding_root`` — see module docstring on downloads."""

    pretrained_file_name_sha1 = {
        f: None for f in (
            ["glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
             "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt"]
            + ["glove.twitter.27B.%dd.txt" % d for d in (25, 50, 100, 200)])}

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root, pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(_TokenEmbedding):
    """fastText .vec files (fasttext.cc); loaded from ``embedding_root``."""

    pretrained_file_name_sha1 = {
        f: None for f in ("wiki.simple.vec", "wiki.zh.vec", "wiki.en.vec")}

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root, pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


class CustomEmbedding(_TokenEmbedding):
    """User-provided embedding file of ``token<delim>v1<delim>...`` lines."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings' vectors over one vocabulary."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        super().__init__()
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = (list(vocabulary.reserved_tokens)
                                 if vocabulary.reserved_tokens else None)
        parts = []
        for emb in token_embeddings:
            sel = np.array([emb.token_to_idx.get(t, UNKNOWN_IDX)
                            for t in self._idx_to_token], np.int32)
            parts.append(emb.idx_to_vec.asnumpy()[sel])
        mat = np.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = nd.array(mat)
