"""SVRG optimization (parity: python/mxnet/contrib/svrg_optimization/)."""
from .svrg_module import SVRGModule
from .svrg_optimizer import _SVRGOptimizer, _AssignmentOptimizer
