"""SVRGModule — stochastic variance-reduced gradient training (parity:
python/mxnet/contrib/svrg_optimization/svrg_module.py:30).

Algorithm (Johnson & Zhang 2013): every ``update_freq`` epochs snapshot
the weights w~ and compute the full-dataset gradient g~; each batch then
steps with ``g(w) - g(w~) + g~`` instead of ``g(w)``.

TPU design: the snapshot lives in a second Module bound to the same
symbol, so both per-batch gradient evaluations are compiled XLA programs
over device-resident params; the SVRG combination is device-side NDArray
arithmetic (no host roundtrip).  The fused single-program step is
disabled here on purpose — SVRG must edit gradients between backward and
update, which is exactly the eager grad_dict contract.  In distributed
mode full gradients are aggregated through the kvstore under ``*_full``
keys via ``_SVRGOptimizer`` (reference svrg_module.py:292-358).
"""
import logging

from ...module.module import Module
from .svrg_optimizer import _SVRGOptimizer


class SVRGModule(Module):
    """Module with SVRG gradient updates every ``update_freq`` epochs."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, update_freq=None, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        if not isinstance(update_freq, int) or update_freq <= 0:
            raise ValueError("update_freq must be a positive integer, "
                             "got %r" % (update_freq,))
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        # name -> NDArray: average full-dataset gradient at the snapshot
        self._param_dict = {}

    # -- lifecycle ---------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, shared_module,
                               grad_req)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        super().init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params, allow_missing=allow_missing,
                            force_init=force_init, allow_extra=allow_extra)
        if self._mod_aux.binded:
            arg, aux = self.get_params()
            self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                      force_init=True, allow_extra=True)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        super().init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        # SVRG edits grad_dict between backward and update; the fused
        # one-program step has no such seam
        self._drop_fused()
        if self._update_on_kvstore and self._kvstore is not None:
            # server must assign *_full keys and optimize the rest
            self._optimizer = _SVRGOptimizer(
                default_optimizer=self._optimizer)
            self._kvstore.set_optimizer(self._optimizer)
        from ... import ndarray as nd
        for name in self._param_names:
            w = self._exec.arg_dict[name]
            self._param_dict[name] = nd.zeros(w.shape, dtype=w.dtype)

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        super().forward(data_batch, is_train)
        if is_train is None:
            is_train = self.for_training
        if is_train and self._mod_aux.binded:
            self._mod_aux.forward(data_batch, is_train=True)

    def forward_backward(self, data_batch):
        # always the eager two-pass path (see init_optimizer)
        self.forward(data_batch, is_train=True)
        self.backward()

    def backward(self, out_grads=None):
        super().backward(out_grads)
        if self._mod_aux.binded:
            self._mod_aux.backward(out_grads)

    def update(self):
        self._update_svrg_gradients()
        super().update()

    # -- SVRG machinery ----------------------------------------------------
    def update_full_grads(self, train_data):
        """Snapshot current weights into the aux module and average the
        gradient over the whole of ``train_data`` (reference :292)."""
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg_params=arg, aux_params=aux)
        train_data.reset()
        for name in self._param_names:
            self._param_dict[name][:] = 0
        nbatch = 0
        padding = 0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            nbatch += 1
            for name in self._param_names:
                g = self._mod_aux._exec.grad_dict.get(name)
                if g is not None:
                    self._param_dict[name] += g
            padding = batch.pad or 0
        true_num_batch = nbatch - padding / train_data.batch_size
        for name in self._param_names:
            self._param_dict[name] /= true_num_batch
        if self._kvstore is not None and self._kvstore.type.startswith("dist"):
            self._accumulate_kvstore()

    def _accumulate_kvstore(self):
        """Aggregate full grads across workers through ``*_full`` keys."""
        kv = self._kvstore
        for name in self._param_names:
            key = name + "_full"
            if key not in getattr(kv, "_store", {}):
                from ... import ndarray as nd
                kv.init(key, nd.zeros_like(self._param_dict[name]))
            kv.push(key, self._param_dict[name])
            kv._barrier()
            kv.pull(key, self._param_dict[name], ignore_sparse=False)
            self._param_dict[name] /= kv.num_workers

    def _update_svrg_gradients(self):
        """grad <- g(w) - g(w~) + g~ , all device-side (reference :360)."""
        for name in self._param_names:
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            g_aux = self._mod_aux._exec.grad_dict[name]
            g[:] = g - g_aux + self._param_dict[name]

    # -- training loop -----------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """BaseModule.fit plus the full-gradient refresh at every
        ``update_freq``-th epoch (reference :395)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ... import metric as _metric
        from ...initializer import Uniform
        from ...model import BatchEndParam
        from ...module.base_module import _as_list

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if eval_metric is not None and \
                not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            if eval_metric is not None:
                eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if eval_metric is not None:
                    self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg, aux)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
