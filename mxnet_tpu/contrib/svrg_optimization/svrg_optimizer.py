"""Optimizers backing SVRGModule (parity: python/mxnet/contrib/
svrg_optimization/svrg_optimizer.py:26,50).

``_AssignmentOptimizer`` turns a kvstore "update" into plain assignment so
full gradients can be accumulated/broadcast through the store;
``_SVRGOptimizer`` routes ``*_full`` keys to assignment and everything
else to the user's real optimizer.  Both exist for the distributed
(update-on-kvstore) path and are registered like any other optimizer.
"""
from ... import optimizer as _opt


@_opt.register
class _AssignmentOptimizer(_opt.Optimizer):
    """kvstore helper: store the pushed (aggregated) gradient as the value."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        weight[:] = grad


@_opt.register
class _SVRGOptimizer(_opt.Optimizer):
    """Wrapper dispatching by key: ``*_full`` -> assignment, else the
    wrapped default optimizer."""

    def __init__(self, default_optimizer, **kwargs):
        base_params = self._base_params(**kwargs)
        super().__init__(**base_params)
        if isinstance(default_optimizer, str):
            self.default_opt = _opt.create(default_optimizer, **kwargs)
        else:
            self.default_opt = default_optimizer
        self.aux_opt = _opt.create(_AssignmentOptimizer.__name__)

    @staticmethod
    def _base_params(**kwargs):
        base = ("rescale_grad", "param_idx2name", "wd", "clip_gradient",
                "learning_rate", "lr_scheduler", "sym", "begin_num_update",
                "multi_precision", "param_dict")
        return {k: v for k, v in kwargs.items() if k in base}

    def _is_full_key(self, index):
        name = index
        if isinstance(index, int):
            name = self.idx2name.get(index, "")
        return isinstance(name, str) and name.endswith("_full")

    def create_state(self, index, weight):
        if self._is_full_key(index):
            return self.aux_opt.create_state(index, weight)
        return self.default_opt.create_state(index, weight)

    def update(self, index, weight, grad, state):
        if self._is_full_key(index):
            self.aux_opt.update(index, weight, grad, state)
        else:
            self.default_opt.update(index, weight, grad, state)
