"""Post-training int8 quantization (parity:
python/mxnet/contrib/quantization.py:84-205 — quantize_model with
naive/entropy calibration over the quantize_graph_pass).

The graph pass rewrites FullyConnected / Convolution nodes into
quantize → int8 compute (int32 accumulate) → dequantize subgraphs; ranges
come from calibration ('naive' min/max or 'entropy' KL-optimal thresholds)
or are computed at runtime when calib_mode='none'.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ops import registry as _registry
from ..symbol.symbol import Node, Symbol

__all__ = ["quantize_model", "quantize_graph", "_get_optimal_threshold"]

_QUANTIZABLE = {"FullyConnected": "_contrib_quantized_fully_connected",
                "Convolution": "_contrib_quantized_conv"}


def quantize_graph(sym, excluded_sym_names=(), quantized_dtype="int8",
                   th_dict=None):
    """Rewrite quantizable nodes into int8 subgraphs (reference
    quantize_graph_pass.cc). th_dict maps node name -> (min, max) of the
    node's DATA input from calibration."""
    th_dict = th_dict or {}
    excluded = set(excluded_sym_names)
    mapping = {}  # id(old_node) -> new Node

    def mapped_entry(entry):
        node, idx = entry
        return (mapping[id(node)], idx)

    for node in sym._topo():
        if node.is_variable:
            mapping[id(node)] = node
            continue
        op_name = node.op.name
        if op_name in _QUANTIZABLE and node.name not in excluded:
            data_e = mapped_entry(node.inputs[0])
            weight_e = mapped_entry(node.inputs[1])
            no_bias = node.params.get("no_bias", False)
            bias_e = mapped_entry(node.inputs[2]) \
                if not no_bias and len(node.inputs) > 2 else None
            qv2 = _registry.get("_contrib_quantize_v2")
            if not data_e[0].is_variable and \
                    data_e[0].op.name == "_contrib_dequantize":
                # upstream already lives in the int8 domain (a requantized
                # conv/FC or quantized pooling/concat): consume its
                # (q, min, max) triple directly — the dequantize/quantize
                # round-trip between consecutive quantized layers is
                # elided, exactly what reference quantize_graph_pass.cc
                # achieves with its requantize chaining
                t = data_e[0].inputs
                d_trip = [t[0], t[1], t[2]]
            else:
                q_params = {"out_type": quantized_dtype}
                if node.name in th_dict:
                    lo, hi = th_dict[node.name]
                    q_params["min_calib_range"] = float(lo)
                    q_params["max_calib_range"] = float(hi)
                qd = Node(qv2, node.name + "_quantize", [data_e],
                          dict(q_params))
                d_trip = [(qd, 0), (qd, 1), (qd, 2)]
            qw = Node(qv2, node.name + "_quantize_weight", [weight_e],
                      {"out_type": "int8"})
            ins = [d_trip[0], (qw, 0)]
            if bias_e is not None:
                qb = Node(qv2, node.name + "_quantize_bias", [bias_e],
                          {"out_type": "int8"})
                ins.append((qb, 0))
                ranges = [d_trip[1], d_trip[2], (qw, 1), (qw, 2), (qb, 1),
                          (qb, 2)]
            else:
                qb = None
                ranges = [d_trip[1], d_trip[2], (qw, 1), (qw, 2)]
            qparams = dict(node.params)
            if qb is None:
                qparams["no_bias"] = True
            qop = _registry.get(_QUANTIZABLE[op_name])
            # op signature has fixed bias slot; insert a zero-range pair
            if qb is None:
                # reuse weight ranges as placeholder bias ranges; no_bias
                # makes the op ignore the bias inputs entirely
                ins.append((qw, 0))
                ranges += [(qw, 1), (qw, 2)]
            qnode = Node(qop, node.name + "_quantized", ins + ranges,
                         qparams)
            # int32 accumulator -> int8 via requantize (reference inserts
            # one after every int32-output op; calibrated when the node's
            # OUTPUT stats were collected)
            rq_params = {}
            if node.name + "::out" in th_dict:
                lo, hi = th_dict[node.name + "::out"]
                rq_params = {"min_calib_range": float(lo),
                             "max_calib_range": float(hi)}
            rq = Node(_registry.get("_contrib_requantize"),
                      node.name + "_requantize",
                      [(qnode, 0), (qnode, 1), (qnode, 2)], rq_params)
            deq = Node(_registry.get("_contrib_dequantize"),
                       node.name + "_dequantize",
                       [(rq, 0), (rq, 1), (rq, 2)], {})
            mapping[id(node)] = deq
        elif op_name in ("Pooling", "Flatten", "Concat") \
                and node.name not in excluded \
                and _all_dequantized(node, mapping):
            # stay in the int8 domain across shape/pool/concat layers
            # between quantized matmul islands (reference
            # quantize_graph_pass.cc keeps these quantized so consecutive
            # conv/FC layers skip the dequantize->requantize round-trip):
            # consume the (q, min, max) feeding the dequantize directly
            triples = [mapping[id(e[0])].inputs for e in node.inputs]
            if op_name == "Concat":
                qop = _registry.get("_contrib_quantized_concat")
                ins = [t[0] for t in triples] + \
                    [r for t in triples for r in (t[1], t[2])]
            elif op_name == "Pooling":
                qop = _registry.get("_contrib_quantized_pooling")
                ins = [triples[0][0], triples[0][1], triples[0][2]]
            else:
                qop = _registry.get("_contrib_quantized_flatten")
                ins = [triples[0][0], triples[0][1], triples[0][2]]
            qnode = Node(qop, node.name + "_quantized", ins,
                         dict(node.params))
            deq = Node(_registry.get("_contrib_dequantize"),
                       node.name + "_dequantize",
                       [(qnode, 0), (qnode, 1), (qnode, 2)], {})
            mapping[id(node)] = deq
        else:
            new_inputs = [mapped_entry(e) for e in node.inputs]
            mapping[id(node)] = Node(node.op, node.name, new_inputs,
                                     dict(node.params), dict(node.attrs))
    return Symbol([(mapping[id(n)], i) for n, i in sym._entries])


def _all_dequantized(node, mapping):
    """Every input of ``node`` maps to a _contrib_dequantize island."""
    for (src, _idx) in node.inputs:
        m = mapping.get(id(src))
        if m is None or m.is_variable or \
                m.op.name != "_contrib_dequantize":
            return False
    return True


def _collect_layer_stats(sym, arg_params, aux_params, calib_data,
                         data_names, num_calib_examples, collect):
    """Run forward passes over calibration batches, feeding `collect` with
    per-quantizable-layer input activations."""
    from .. import ndarray as nd
    from ..executor import _graph_eval_fn

    # internals symbol exposing each quantizable node's data input AND
    # its output (the output ranges calibrate the post-accumulator
    # requantize, reference quantization.py collects both)
    targets = {}
    for node in sym._topo():
        if not node.is_variable and node.op.name in _QUANTIZABLE:
            targets[node.name] = node.inputs[0]
            targets[node.name + "::out"] = (node, 0)
    if not targets:
        return
    probe = Symbol(list(targets.values()))
    eval_fn = _graph_eval_fn(probe)
    import jax
    key = jax.random.PRNGKey(0)
    seen = 0
    calib_data.reset()
    for batch in calib_data:
        arg_vals = {}
        for name, arr in zip(data_names, batch.data):
            arg_vals[name] = arr._data if hasattr(arr, "_data") else arr
        for k, v in arg_params.items():
            arg_vals[k] = v._data if hasattr(v, "_data") else v
        aux_vals = {k: (v._data if hasattr(v, "_data") else v)
                    for k, v in aux_params.items()}
        outs, _ = eval_fn(arg_vals, aux_vals, key, False)
        for lname, out in zip(targets.keys(), outs):
            collect(lname, _np.asarray(out))
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break


def _smooth_distribution(p, eps=0.0001):
    """Move eps mass to zero entries (reference _smooth_distribution)."""
    is_zeros = (p == 0).astype(_np.float64)
    n_zeros = is_zeros.sum()
    n_nonzeros = p.size - n_zeros
    if n_nonzeros == 0:
        return None
    eps1 = eps * n_zeros / n_nonzeros
    return p.astype(_np.float64) - eps1 * (1 - is_zeros) + eps * is_zeros


def _get_optimal_threshold(arr, num_bins=1601, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| (reference
    _get_optimal_thresholds / TensorRT-style calibration,
    contrib/quantization.py)."""
    arr = _np.asarray(arr).ravel()
    amax = float(_np.abs(arr).max()) if arr.size else 0.0
    if amax == 0.0:
        return 0.0
    hist, edges = _np.histogram(arr, bins=num_bins, range=(-amax, amax))
    zero_bin = num_bins // 2
    half_q = num_quantized_bins // 2
    best_div, best_t = _np.inf, amax
    for i in range(half_q + 1, zero_bin + 1):
        p_start, p_stop = zero_bin - i, zero_bin + i + 1
        sliced = hist[p_start:p_stop].astype(_np.float64)
        p = sliced.copy()
        # clipped outlier mass lands in the edge bins
        p[0] += hist[:p_start].sum()
        p[-1] += hist[p_stop:].sum()
        is_nonzero = (p != 0)
        # quantize the candidate range into num_quantized_bins, then expand
        # each quantized bin's mass uniformly over its NONZERO source bins
        num_merged = p.size // num_quantized_bins
        q = _np.zeros_like(p)
        for j in range(num_quantized_bins):
            start = j * num_merged
            stop = p.size if j == num_quantized_bins - 1 \
                else start + num_merged
            total = sliced[start:stop].sum()
            norm = is_nonzero[start:stop].sum()
            if norm:
                q[start:stop] = is_nonzero[start:stop] * (total / norm)
        p_s = _smooth_distribution(p / p.sum())
        q_sum = q.sum()
        if p_s is None or q_sum == 0:
            continue
        q_s = _smooth_distribution(q / q_sum)
        if q_s is None:
            continue
        div = float(_np.sum(p_s * _np.log(p_s / q_s)))
        if div < best_div:
            best_div = div
            best_t = (i + 0.5) * (2.0 * amax / num_bins)
    return best_t


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None,
                   label_names=("softmax_label",), logger=None):
    """Quantize a symbolic model (reference quantize_model :84-205).

    Returns (quantized_symbol, arg_params, aux_params); parameters stay
    fp32 (quantization happens in-graph, so checkpoints remain portable).
    """
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise ValueError("unknown quantized_dtype %s" % quantized_dtype)
    if quantized_dtype == "auto":
        quantized_dtype = "int8"
    th_dict = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_data required for calib_mode=%r"
                             % calib_mode)
        stats = {}

        def collect(name, arr):
            lo, hi = float(arr.min()), float(arr.max())
            if calib_mode == "naive":
                if name in stats:
                    stats[name] = (min(stats[name][0], lo),
                                   max(stats[name][1], hi))
                else:
                    stats[name] = (lo, hi)
            else:  # entropy: keep samples for KL thresholding
                stats.setdefault(name, []).append(arr.ravel())

        _collect_layer_stats(sym, arg_params, aux_params, calib_data,
                             data_names, num_calib_examples, collect)
        if calib_mode == "naive":
            th_dict = dict(stats)
        elif calib_mode == "entropy":
            for name, chunks in stats.items():
                t = _get_optimal_threshold(_np.concatenate(chunks))
                th_dict[name] = (-t, t)
        else:
            raise ValueError("unknown calib_mode %s" % calib_mode)
    qsym = quantize_graph(sym, excluded_sym_names, quantized_dtype, th_dict)
    return qsym, arg_params, aux_params
