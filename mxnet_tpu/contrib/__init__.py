"""Contrib APIs (parity: python/mxnet/contrib/)."""
from . import quantization
