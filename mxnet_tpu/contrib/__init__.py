"""Contrib APIs (parity: python/mxnet/contrib/)."""
from . import autograd
from . import io
from . import onnx
from . import quantization
from . import svrg_optimization
from . import tensorboard
from . import text
