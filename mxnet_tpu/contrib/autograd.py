"""The pre-1.0 autograd API (parity: python/mxnet/contrib/autograd.py).

Thin facade over :mod:`mxnet_tpu.autograd` — v0.x scripts that used
``train_section()`` / ``compute_gradient`` / ``grad_and_loss`` keep
working; the modern module is the real implementation.
"""
import functools

from .. import autograd as _ag
from ..ndarray import ndarray as _nd

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Set training mode AND recording (the old API conflated the two);
    returns the previous recording state."""
    prev = _ag.is_recording()
    _ag.set_recording(is_train)
    _ag.set_training(is_train)
    return prev


def train_section():
    """Context manager: record operations for autograd (old name)."""
    return _ag.record(train_mode=True)


def test_section():
    """Context manager: stop recording inside a train_section (old name)."""
    return _ag.pause(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    if not isinstance(outputs, (list, tuple)):
        raise TypeError("outputs must be a list or tuple of NDArrays")
    _ag.backward(list(outputs), head_grads=out_grads,
                 retain_graph=retain_graph)


def compute_gradient(outputs):
    """Deprecated old name for :func:`backward`."""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Wrap ``func`` to return ``(grads, outputs)`` of selected args."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            nums = argnum if isinstance(argnum, list) else [argnum]
            variables = [args[i] for i in nums]
        for x in variables:
            if not isinstance(x, _nd.NDArray):
                raise TypeError("autograd input must be NDArray")
        grads = [_nd.zeros_like(x) for x in variables]
        _ag.mark_variables(variables, grads)
        with _ag.record():
            outputs = func(*args)
        _ag.backward([outputs] if isinstance(outputs, _nd.NDArray)
                     else list(outputs))
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Wrap ``func`` to return only the gradients of selected args."""
    fn = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return fn(*args)[0]
    return wrapped
