"""``mx.contrib.symbol`` namespace (reference contrib/symbol.py).
Re-exports the real surface from :mod:`mxnet_tpu.symbol.contrib`."""
from ..symbol.contrib import foreach, while_loop, cond  # noqa: F401
