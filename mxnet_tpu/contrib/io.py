"""Contrib data iterators (parity: python/mxnet/contrib/io.py:24).

``DataLoaderIter`` adapts a ``gluon.data.DataLoader`` to the symbolic
``DataIter`` interface so Gluon pipelines feed ``Module.fit`` — short
final batches are padded up to ``batch_size`` (static shapes keep XLA
from recompiling on the tail batch) and ``getpad`` reports the padding.
"""
from ..io.io import DataIter, DataDesc
from .. import ndarray as nd


class DataLoaderIter(DataIter):
    """Iterate a gluon DataLoader as a DataIter."""

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        data, label = next(self._iter)
        self.batch_size = data.shape[0]
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, tuple(data.shape), dtype)]
        self.provide_label = [DataDesc(label_name, tuple(label.shape), dtype)]
        self._current_batch = None
        self.reset()

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        try:
            self._current_batch = next(self._iter)
        except StopIteration:
            self._current_batch = None
        return self._current_batch is not None

    def _padded(self, arr):
        arr = arr.astype(self.dtype) if arr.dtype != self.dtype else arr
        pad = self.batch_size - arr.shape[0]
        if pad:
            ret = nd.zeros((self.batch_size,) + tuple(arr.shape[1:]),
                           dtype=self.dtype)
            ret[:arr.shape[0]] = arr
            return ret
        return arr

    def getdata(self):
        return [self._padded(self._current_batch[0])]

    def getlabel(self):
        return [self._padded(self._current_batch[1])]

    def getpad(self):
        return self.batch_size - self._current_batch[0].shape[0]

    def getindex(self):
        return None
