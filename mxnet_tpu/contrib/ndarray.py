"""``mx.contrib.ndarray`` namespace (reference contrib/ndarray.py —
the registration target for contrib ndarray functions). Re-exports the
real surface from :mod:`mxnet_tpu.ndarray.contrib`."""
from ..ndarray.contrib import *  # noqa: F401,F403
from ..ndarray.contrib import foreach, while_loop, cond  # noqa: F401
