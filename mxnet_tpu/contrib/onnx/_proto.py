"""Minimal protobuf wire codec for the ONNX messages this package uses.

The environment has no ``onnx`` python package, so serialization is done
directly against the (stable, versioned) protobuf wire format of
onnx.proto — the subset of messages/fields needed for model import and
export: ModelProto, GraphProto, NodeProto, AttributeProto, TensorProto,
ValueInfoProto, TypeProto, TensorShapeProto, OperatorSetIdProto.

Field kinds: ``int`` (varint), ``float`` (fixed32), ``string``/``bytes``
(length-delimited), ``msg`` (embedded message).  Repeated scalar numerics
accept both packed and unpacked encodings on decode and emit packed, per
proto3.  Unknown fields are skipped on decode, so files produced by full
ONNX implementations parse fine.
"""
import struct


# ---------------------------------------------------------------- wire io
def _enc_varint(out, v):
    if v < 0:
        v &= (1 << 64) - 1  # two's-complement int64, as protobuf does
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _dec_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return result, pos


def _signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def _skip(buf, pos, wire_type):
    if wire_type == 0:
        _, pos = _dec_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        n, pos = _dec_varint(buf, pos)
        pos += n
    elif wire_type == 5:
        pos += 4
    else:
        raise ValueError("unsupported wire type %d" % wire_type)
    return pos


_WIRE = {"int": 0, "float": 5, "string": 2, "bytes": 2, "msg": 2}


class Message:
    """Base: subclasses define FIELDS = {name: (field_no, kind, repeated[, cls])}."""

    FIELDS = {}

    def __init__(self, **kwargs):
        for name, spec in self.FIELDS.items():
            setattr(self, name, [] if spec[2] else _default(spec[1]))
        for k, v in kwargs.items():
            if k not in self.FIELDS:
                raise AttributeError("%s has no field %r"
                                     % (type(self).__name__, k))
            setattr(self, k, v)

    # -- encode ------------------------------------------------------------
    def encode(self):
        out = bytearray()
        for name, spec in self.FIELDS.items():
            num, kind, repeated = spec[0], spec[1], spec[2]
            val = getattr(self, name)
            if repeated:
                if not val:
                    continue
                if kind == "int":       # packed
                    payload = bytearray()
                    for v in val:
                        _enc_varint(payload, int(v))
                    _enc_varint(out, num << 3 | 2)
                    _enc_varint(out, len(payload))
                    out += payload
                elif kind == "float":   # packed
                    payload = struct.pack("<%df" % len(val), *val)
                    _enc_varint(out, num << 3 | 2)
                    _enc_varint(out, len(payload))
                    out += payload
                else:
                    for v in val:
                        self._enc_one(out, num, kind, v)
            else:
                if _is_default(kind, val):
                    continue
                self._enc_one(out, num, kind, val)
        return bytes(out)

    @staticmethod
    def _enc_one(out, num, kind, val):
        _enc_varint(out, num << 3 | _WIRE[kind])
        if kind == "int":
            _enc_varint(out, int(val))
        elif kind == "float":
            out += struct.pack("<f", val)
        elif kind == "string":
            data = val.encode("utf-8")
            _enc_varint(out, len(data))
            out += data
        elif kind == "bytes":
            _enc_varint(out, len(val))
            out += val
        elif kind == "msg":
            data = val.encode()
            _enc_varint(out, len(data))
            out += data

    # -- decode ------------------------------------------------------------
    @classmethod
    def decode(cls, buf, start=0, end=None):
        self = cls()
        by_num = {spec[0]: (name, spec) for name, spec in cls.FIELDS.items()}
        pos = start
        end = len(buf) if end is None else end
        while pos < end:
            key, pos = _dec_varint(buf, pos)
            num, wt = key >> 3, key & 7
            if num not in by_num:
                pos = _skip(buf, pos, wt)
                continue
            name, spec = by_num[num]
            kind, repeated = spec[1], spec[2]
            if kind == "int":
                if wt == 2:  # packed
                    n, pos = _dec_varint(buf, pos)
                    stop = pos + n
                    vals = []
                    while pos < stop:
                        v, pos = _dec_varint(buf, pos)
                        vals.append(_signed64(v))
                    if repeated:
                        getattr(self, name).extend(vals)
                    elif vals:
                        # empty packed payload on a scalar field: keep the
                        # default rather than crash on a truncated file
                        setattr(self, name, vals[-1])
                else:
                    v, pos = _dec_varint(buf, pos)
                    v = _signed64(v)
                    getattr(self, name).append(v) if repeated \
                        else setattr(self, name, v)
            elif kind == "float":
                if wt == 2:  # packed
                    n, pos = _dec_varint(buf, pos)
                    vals = list(struct.unpack_from("<%df" % (n // 4), buf, pos))
                    pos += n
                    getattr(self, name).extend(vals) if repeated \
                        else setattr(self, name, vals[-1])
                else:
                    v = struct.unpack_from("<f", buf, pos)[0]
                    pos += 4
                    getattr(self, name).append(v) if repeated \
                        else setattr(self, name, v)
            elif kind in ("string", "bytes", "msg"):
                n, pos = _dec_varint(buf, pos)
                raw = bytes(buf[pos:pos + n])
                pos += n
                if kind == "string":
                    v = raw.decode("utf-8")
                elif kind == "bytes":
                    v = raw
                else:
                    v = spec[3].decode(raw)
                getattr(self, name).append(v) if repeated \
                    else setattr(self, name, v)
        return self

    def __repr__(self):
        parts = []
        for name in self.FIELDS:
            v = getattr(self, name)
            if v not in (None, [], "", b"", 0, 0.0):
                parts.append("%s=%r" % (name, v))
        return "%s(%s)" % (type(self).__name__, ", ".join(parts))


def _default(kind):
    return {"int": 0, "float": 0.0, "string": "", "bytes": b"",
            "msg": None}[kind]


def _is_default(kind, val):
    if kind == "msg":
        return val is None
    return val == _default(kind)


# ------------------------------------------------------------ onnx schema
class TensorProto(Message):
    # onnx.TensorProto.DataType
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
    BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 9, 10, 11, 12, 13
    BFLOAT16 = 16
    FIELDS = {
        "dims": (1, "int", True),
        "data_type": (2, "int", False),
        "float_data": (4, "float", True),
        "int32_data": (5, "int", True),
        "string_data": (6, "bytes", True),
        "int64_data": (7, "int", True),
        "name": (8, "string", False),
        "raw_data": (9, "bytes", False),
    }


class Dimension(Message):
    FIELDS = {
        "dim_value": (1, "int", False),
        "dim_param": (2, "string", False),
    }


class TensorShapeProto(Message):
    FIELDS = {"dim": (1, "msg", True, Dimension)}


class TensorTypeProto(Message):
    FIELDS = {
        "elem_type": (1, "int", False),
        "shape": (2, "msg", False, TensorShapeProto),
    }


class TypeProto(Message):
    FIELDS = {"tensor_type": (1, "msg", False, TensorTypeProto)}


class ValueInfoProto(Message):
    FIELDS = {
        "name": (1, "string", False),
        "type": (2, "msg", False, TypeProto),
        "doc_string": (3, "string", False),
    }


class AttributeProto(Message):
    # onnx.AttributeProto.AttributeType
    FLOAT, INT, STRING, TENSOR = 1, 2, 3, 4
    GRAPH, FLOATS, INTS, STRINGS = 5, 6, 7, 8
    FIELDS = {
        "name": (1, "string", False),
        "f": (2, "float", False),
        "i": (3, "int", False),
        "s": (4, "bytes", False),
        "t": (5, "msg", False, TensorProto),
        "floats": (7, "float", True),
        "ints": (8, "int", True),
        "strings": (9, "bytes", True),
        "type": (20, "int", False),
    }


class NodeProto(Message):
    FIELDS = {
        "input": (1, "string", True),
        "output": (2, "string", True),
        "name": (3, "string", False),
        "op_type": (4, "string", False),
        "attribute": (5, "msg", True, AttributeProto),
        "doc_string": (6, "string", False),
        "domain": (7, "string", False),
    }


class GraphProto(Message):
    FIELDS = {
        "node": (1, "msg", True, NodeProto),
        "name": (2, "string", False),
        "initializer": (5, "msg", True, TensorProto),
        "doc_string": (10, "string", False),
        "input": (11, "msg", True, ValueInfoProto),
        "output": (12, "msg", True, ValueInfoProto),
        "value_info": (13, "msg", True, ValueInfoProto),
    }


class OperatorSetIdProto(Message):
    FIELDS = {
        "domain": (1, "string", False),
        "version": (2, "int", False),
    }


class ModelProto(Message):
    FIELDS = {
        "ir_version": (1, "int", False),
        "producer_name": (2, "string", False),
        "producer_version": (3, "string", False),
        "domain": (4, "string", False),
        "model_version": (5, "int", False),
        "doc_string": (6, "string", False),
        "graph": (7, "msg", False, GraphProto),
        "opset_import": (8, "msg", True, OperatorSetIdProto),
    }
