"""ONNX import/export (parity: python/mxnet/contrib/onnx/).

Self-contained: serializes against the ONNX protobuf wire format directly
(no ``onnx`` package dependency), see ``_proto.py``.
"""
from .onnx2mx import import_model, get_model_metadata
from .mx2onnx import export_model
