"""mxnet_tpu -> ONNX graph exporter (parity: python/mxnet/contrib/onnx/
mx2onnx/export_model.py + _op_translations.py).

Serializes a Symbol + params to an ONNX ModelProto (opset 9) covering the
op subset the reference's exporter handles for MLP/CNN inference graphs.
Training-only heads (SoftmaxOutput, *RegressionOutput) export as their
inference forms, as in the reference.
"""
import numpy as _np

from . import _proto as P
from ...base import MXNetError

_OPSET = 9

_NP_TO_ONNX = {
    _np.dtype(_np.float32): P.TensorProto.FLOAT,
    _np.dtype(_np.float16): P.TensorProto.FLOAT16,
    _np.dtype(_np.float64): P.TensorProto.DOUBLE,
    _np.dtype(_np.int32): P.TensorProto.INT32,
    _np.dtype(_np.int64): P.TensorProto.INT64,
    _np.dtype(_np.uint8): P.TensorProto.UINT8,
    _np.dtype(_np.int8): P.TensorProto.INT8,
    _np.dtype(_np.bool_): P.TensorProto.BOOL,
}


def numpy_to_tensor(arr, name):
    arr = _np.ascontiguousarray(arr)
    if arr.dtype not in _NP_TO_ONNX:
        raise MXNetError("cannot export dtype %s" % arr.dtype)
    return P.TensorProto(name=name, dims=list(arr.shape),
                         data_type=_NP_TO_ONNX[arr.dtype],
                         raw_data=arr.tobytes())


def _value_info(name, shape, elem_type=P.TensorProto.FLOAT):
    dims = [P.Dimension(dim_value=int(d)) for d in shape]
    return P.ValueInfoProto(
        name=name,
        type=P.TypeProto(tensor_type=P.TensorTypeProto(
            elem_type=elem_type,
            shape=P.TensorShapeProto(dim=dims))))


def _attr_i(name, v):
    return P.AttributeProto(name=name, i=int(v), type=P.AttributeProto.INT)


def _attr_f(name, v):
    return P.AttributeProto(name=name, f=float(v),
                            type=P.AttributeProto.FLOAT)


def _attr_ints(name, vs):
    return P.AttributeProto(name=name, ints=[int(v) for v in vs],
                            type=P.AttributeProto.INTS)


def _attr_s(name, v):
    return P.AttributeProto(name=name, s=v.encode("utf-8"),
                            type=P.AttributeProto.STRING)


class _Exporter:
    def __init__(self, sym, params):
        self.sym = sym
        self.params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else
                           _np.asarray(v)) for k, v in params.items()}
        self.nodes = []
        self.initializers = []
        self.extra_inits = set()

    def _vname(self, node, idx):
        if node.is_variable:
            return node.name
        if node.num_outputs() > 1:
            return "%s_out%d" % (node.name, idx)
        return node.name

    def _ins(self, node, n=None):
        ents = node.inputs if n is None else node.inputs[:n]
        return [self._vname(p, i) for p, i in ents]

    def _emit(self, op_type, inputs, outputs, name, attrs=()):
        self.nodes.append(P.NodeProto(op_type=op_type, input=list(inputs),
                                      output=list(outputs), name=name,
                                      attribute=list(attrs)))

    def _shape_init(self, name, values):
        """int64 constant initializer (e.g. Reshape target shape)."""
        self.initializers.append(
            numpy_to_tensor(_np.asarray(values, _np.int64), name))
        self.extra_inits.add(name)

    def _scalar_init(self, name, value):
        self.initializers.append(
            numpy_to_tensor(_np.asarray(value, _np.float32), name))
        self.extra_inits.add(name)

    def run(self, input_shapes, input_dtype):
        sym = self.sym
        topo = sym._topo()
        args = sym.list_arguments()
        aux = set(sym.list_auxiliary_states())

        for node in topo:
            if node.is_variable:
                continue
            self._convert(node)

        # only variables the emitted nodes actually reference become graph
        # inputs — training heads drop their label inputs here, like the
        # reference exporter
        used = {n for nd_ in self.nodes for n in nd_.input}
        data_names = [n for n in args
                      if n not in self.params and n in used]
        if len(data_names) != len(input_shapes):
            raise MXNetError(
                "export_model: %d data inputs (%s) but %d input_shapes"
                % (len(data_names), data_names, len(input_shapes)))

        graph_inputs = [
            _value_info(n, s, _NP_TO_ONNX[_np.dtype(input_dtype)])
            for n, s in zip(data_names, input_shapes)]
        for name in list(args) + sorted(aux):
            if name in self.params and name in used:
                self.initializers.append(
                    numpy_to_tensor(self.params[name], name))
                graph_inputs.append(
                    _value_info(name, self.params[name].shape,
                                _NP_TO_ONNX[self.params[name].dtype]))

        outputs = []
        out_shapes = None
        try:
            shape_kwargs = dict(zip(data_names, input_shapes))
            _, out_shapes, _ = sym.infer_shape(**shape_kwargs)
        except Exception:
            pass
        for i, (ent, oi) in enumerate(sym._entries):
            vi_name = self._vname(ent, oi)
            shape = out_shapes[i] if out_shapes else ()
            outputs.append(_value_info(vi_name, shape))

        graph = P.GraphProto(node=self.nodes, name="mxnet_tpu_model",
                             initializer=self.initializers,
                             input=graph_inputs, output=outputs)
        return P.ModelProto(
            ir_version=4, producer_name="mxnet_tpu",
            producer_version="0.1", graph=graph,
            opset_import=[P.OperatorSetIdProto(domain="", version=_OPSET)])

    # -- op translations ---------------------------------------------------
    def _convert(self, node):
        fn = _TRANSLATIONS.get(node.op.name)
        if fn is None:
            raise MXNetError("op %r has no ONNX translation"
                             % node.op.name)
        fn(self, node, node.params)


def _simple(onnx_op, attr_fn=None, n_in=None):
    def tr(ex, node, p):
        attrs = attr_fn(p) if attr_fn else ()
        ex._emit(onnx_op, ex._ins(node, n_in), [ex._vname(node, 0)],
                 node.name, attrs)
    return tr


def _tr_fc(ex, node, p):
    ins = ex._ins(node)
    data = ins[0]
    if not p.get("no_bias", False) and len(ins) < 3:
        raise MXNetError("FullyConnected with implicit bias slot")
    if p.get("flatten", True):
        flat = node.name + "_flat"
        ex._emit("Flatten", [data], [flat], flat, [_attr_i("axis", 1)])
        data = flat
    gemm_in = [data, ins[1]] + ([ins[2]] if len(ins) > 2 else [])
    ex._emit("Gemm", gemm_in, [ex._vname(node, 0)], node.name,
             [_attr_f("alpha", 1.0), _attr_f("beta", 1.0),
              _attr_i("transA", 0), _attr_i("transB", 1)])


def _tr_conv(ex, node, p):
    kernel = tuple(p["kernel"])
    n = len(kernel)
    attrs = [
        _attr_ints("kernel_shape", kernel),
        _attr_ints("strides", p.get("stride") or (1,) * n),
        _attr_ints("dilations", p.get("dilate") or (1,) * n),
        _attr_ints("pads", tuple(p.get("pad") or (0,) * n) * 2),
        _attr_i("group", p.get("num_group", 1)),
    ]
    ex._emit("Conv", ex._ins(node), [ex._vname(node, 0)], node.name, attrs)


def _tr_pool(ex, node, p):
    pool_type = p.get("pool_type", "max")
    if pool_type not in ("max", "avg"):
        raise MXNetError("pool_type %r not exportable" % pool_type)
    if p.get("global_pool", False):
        op = "GlobalMaxPool" if pool_type == "max" else "GlobalAveragePool"
        ex._emit(op, ex._ins(node), [ex._vname(node, 0)], node.name)
        return
    kernel = tuple(p["kernel"])
    n = len(kernel)
    attrs = [
        _attr_ints("kernel_shape", kernel),
        _attr_ints("strides", p.get("stride") or (1,) * n),
        _attr_ints("pads", tuple(p.get("pad") or (0,) * n) * 2),
    ]
    op = "MaxPool" if pool_type == "max" else "AveragePool"
    if pool_type == "avg":
        attrs.append(_attr_i("count_include_pad",
                             1 if p.get("count_include_pad", True) else 0))
    ex._emit(op, ex._ins(node), [ex._vname(node, 0)], node.name, attrs)


def _tr_bn(ex, node, p):
    attrs = [_attr_f("epsilon", p.get("eps", 1e-3)),
             _attr_f("momentum", p.get("momentum", 0.9))]
    if p.get("fix_gamma", True):
        # mxnet's forward replaces gamma with ones when fix_gamma (the
        # default, ops/nn.py) — export what the model actually computes
        gamma_name = node.inputs[1][0].name
        if gamma_name in ex.params:
            ex.params[gamma_name] = _np.ones_like(ex.params[gamma_name])
    ex._emit("BatchNormalization", ex._ins(node, 5),
             [ex._vname(node, 0)], node.name, attrs)


def _tr_activation(ex, node, p):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = p.get("act_type", "relu")
    if act not in table:
        raise MXNetError("Activation %r not exportable" % act)
    ex._emit(table[act], ex._ins(node), [ex._vname(node, 0)], node.name)


def _tr_leaky(ex, node, p):
    act = p.get("act_type", "leaky")
    if act == "leaky":
        ex._emit("LeakyRelu", ex._ins(node, 1), [ex._vname(node, 0)],
                 node.name, [_attr_f("alpha", p.get("slope", 0.25))])
    elif act == "elu":
        ex._emit("Elu", ex._ins(node, 1), [ex._vname(node, 0)], node.name,
                 [_attr_f("alpha", p.get("slope", 1.0))])
    elif act == "prelu":
        ex._emit("PRelu", ex._ins(node), [ex._vname(node, 0)], node.name)
    else:
        raise MXNetError("LeakyReLU %r not exportable" % act)


def _tr_reshape(ex, node, p):
    shape = tuple(p["shape"])
    # ONNX Reshape only defines 0 (copy) and -1 (infer); mxnet's -2/-3/-4
    # special codes have no ONNX encoding — exporting them verbatim would
    # produce a silently invalid graph
    if any(s < -1 for s in shape):
        raise MXNetError(
            "Reshape node %r uses mxnet special shape codes %r; ONNX "
            "Reshape supports only 0 and -1 — rewrite the model with an "
            "explicit shape before export" % (node.name, shape))
    shape_name = node.name + "_shape"
    ex._shape_init(shape_name, shape)
    ex._emit("Reshape", ex._ins(node) + [shape_name],
             [ex._vname(node, 0)], node.name)


def _tr_scalar(onnx_op, reverse=False):
    def tr(ex, node, p):
        c_name = node.name + "_scalar"
        ex._scalar_init(c_name, p["scalar"])
        ins = ex._ins(node)
        ordered = [c_name, ins[0]] if reverse else [ins[0], c_name]
        ex._emit(onnx_op, ordered, [ex._vname(node, 0)], node.name)
    return tr


def _tr_reduce(onnx_op):
    def tr(ex, node, p):
        attrs = [_attr_i("keepdims", 1 if p.get("keepdims") else 0)]
        ax = p.get("axis")
        if ax is not None and ax != ():
            ax = (ax,) if isinstance(ax, int) else tuple(ax)
            attrs.append(_attr_ints("axes", ax))
        ex._emit(onnx_op, ex._ins(node), [ex._vname(node, 0)],
                 node.name, attrs)
    return tr


def _tr_softmax_output(ex, node, p):
    # inference form: softmax over the scores input only
    ex._emit("Softmax", ex._ins(node, 1), [ex._vname(node, 0)], node.name,
             [_attr_i("axis", 1)])


def _tr_identity_head(ex, node, p):
    ex._emit("Identity", ex._ins(node, 1), [ex._vname(node, 0)], node.name)


def _tr_square(ex, node, p):
    # reference convert_square: Pow against a constant-2 initializer
    cname = node.name + "_pow2"
    ex._scalar_init(cname, 2.0)
    ex._emit("Pow", ex._ins(node, 1) + [cname], [ex._vname(node, 0)],
             node.name)


def _tr_slice_axis(ex, node, p):
    end = p.get("end")
    ex._emit("Slice", ex._ins(node, 1), [ex._vname(node, 0)], node.name,
             [_attr_ints("axes", (p["axis"],)),
              _attr_ints("starts", (p.get("begin", 0),)),
              _attr_ints("ends", (2 ** 31 - 1 if end is None else end,))])


def _tr_split(ex, node, p):
    n_out = int(p.get("num_outputs", 1))
    outs = [ex._vname(node, i) for i in range(n_out)]
    axis = p.get("axis", 1)
    if p.get("squeeze_axis"):
        # ONNX Split keeps the axis; add a Squeeze per output (reference
        # convert_slice_channel squeeze_axis=1 form)
        raw = [o + "_presqueeze" for o in outs]
        ex._emit("Split", ex._ins(node, 1), raw, node.name,
                 [_attr_i("axis", axis)])
        for r, o in zip(raw, outs):
            ex._emit("Squeeze", [r], [o], o + "_squeeze",
                     [_attr_ints("axes", (axis,))])
    else:
        ex._emit("Split", ex._ins(node, 1), outs, node.name,
                 [_attr_i("axis", axis)])


def _tr_pad(ex, node, p):
    pw = tuple(p["pad_width"])
    n = len(pw) // 2
    # MXNet flat (before,after) per axis -> ONNX (begins..., ends...)
    pads = [int(pw[2 * i]) for i in range(n)] \
        + [int(pw[2 * i + 1]) for i in range(n)]
    attrs = [_attr_s("mode", p.get("mode", "constant")),
             _attr_ints("pads", pads)]
    if p.get("mode", "constant") == "constant":
        attrs.append(_attr_f("value", p.get("constant_value", 0.0)))
    ex._emit("Pad", ex._ins(node, 1), [ex._vname(node, 0)], node.name,
             attrs)


def _tr_l2norm(ex, node, p):
    # LpNormalization normalizes along ONE axis; only channel mode maps
    # (the reference exporter likewise refuses non-channel modes)
    if p.get("mode", "instance") != "channel":
        raise MXNetError(
            "L2Normalization mode %r has no ONNX form (only 'channel' "
            "maps to LpNormalization)" % p.get("mode", "instance"))
    ex._emit("LpNormalization", ex._ins(node, 1), [ex._vname(node, 0)],
             node.name, [_attr_i("p", 2), _attr_i("axis", 1)])


def _tr_arg_reduce(onnx_op):
    def tr(ex, node, p):
        axis = p.get("axis")
        if axis is None:
            raise MXNetError("%s without axis has no ONNX form" % onnx_op)
        ex._emit(onnx_op, ex._ins(node, 1), [ex._vname(node, 0)],
                 node.name,
                 [_attr_i("axis", int(axis)),
                  _attr_i("keepdims", 1 if p.get("keepdims") else 0)])
    return tr


_TRANSLATIONS = {
    "FullyConnected": _tr_fc,
    "Convolution": _tr_conv,
    "Pooling": _tr_pool,
    "BatchNorm": _tr_bn,
    "Activation": _tr_activation,
    "LeakyReLU": _tr_leaky,
    "Reshape": _tr_reshape,
    "SoftmaxOutput": _tr_softmax_output,
    "LinearRegressionOutput": _tr_identity_head,
    "LogisticRegressionOutput": lambda ex, node, p: ex._emit(
        "Sigmoid", ex._ins(node, 1), [ex._vname(node, 0)], node.name),
    "MAERegressionOutput": _tr_identity_head,
    "Flatten": _simple("Flatten", lambda p: [_attr_i("axis", 1)]),
    "softmax": _simple("Softmax",
                       lambda p: [_attr_i("axis", p.get("axis", -1))]),
    "transpose": _simple("Transpose",
                         lambda p: [_attr_ints("perm", p["axes"])]
                         if p.get("axes") else []),
    "Concat": lambda ex, node, p: ex._emit(
        "Concat", ex._ins(node), [ex._vname(node, 0)], node.name,
        [_attr_i("axis", p.get("dim", 1))]),
    "Dropout": _simple("Dropout",
                       lambda p: [_attr_f("ratio", p.get("p", 0.5))], n_in=1),
    "clip": _simple("Clip", lambda p: [_attr_f("min", p["a_min"]),
                                       _attr_f("max", p["a_max"])]),
    "dot": _simple("MatMul"),
    "elemwise_add": _simple("Add"),
    "elemwise_sub": _simple("Sub"),
    "elemwise_mul": _simple("Mul"),
    "elemwise_div": _simple("Div"),
    "broadcast_add": _simple("Add"),
    "broadcast_sub": _simple("Sub"),
    "broadcast_mul": _simple("Mul"),
    "broadcast_div": _simple("Div"),
    "broadcast_power": _simple("Pow"),
    "_plus_scalar": _tr_scalar("Add"),
    "_minus_scalar": _tr_scalar("Sub"),
    "_rminus_scalar": _tr_scalar("Sub", reverse=True),
    "_mul_scalar": _tr_scalar("Mul"),
    "_div_scalar": _tr_scalar("Div"),
    "relu": _simple("Relu"),
    "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"),
    "exp": _simple("Exp"),
    "log": _simple("Log"),
    "sqrt": _simple("Sqrt"),
    "negative": _simple("Neg"),
    "abs": _simple("Abs"),
    "mean": _tr_reduce("ReduceMean"),
    "sum": _tr_reduce("ReduceSum"),
    "max": _tr_reduce("ReduceMax"),
    "min": _tr_reduce("ReduceMin"),
    "expand_dims": _simple("Unsqueeze",
                           lambda p: [_attr_ints("axes", (p["axis"],))]),
    # axis=None (squeeze all unit dims) must emit NO axes attribute —
    # an empty-but-present axes list round-trips as a no-op
    "squeeze": _simple(
        "Squeeze",
        lambda p: [_attr_ints("axes", (p["axis"],)
                              if isinstance(p["axis"], int)
                              else tuple(p["axis"]))]
        if p.get("axis") not in (None, ()) else []),
    "cast": lambda ex, node, p: ex._emit(
        "Cast", ex._ins(node), [ex._vname(node, 0)], node.name,
        [_attr_i("to", _NP_TO_ONNX[_np.dtype(p["dtype"])])]),
    # --- remainder of the reference's export table (mx2onnx/
    # _op_translations.py @mx_op.register set) ---
    "_copy": _simple("Identity"),
    "_linalg_gemm2": _simple("MatMul"),
    "_maximum": _simple("Max"),
    "_minimum": _simple("Min"),
    "broadcast_maximum": _simple("Max"),   # ONNX Max/Min broadcast
    "broadcast_minimum": _simple("Min"),
    "_power": _simple("Pow"),
    "add_n": _simple("Sum"),
    "ceil": _simple("Ceil"),
    "floor": _simple("Floor"),
    "reciprocal": _simple("Reciprocal"),
    "square": _tr_square,
    "cos": _simple("Cos"),
    "sin": _simple("Sin"),
    "tan": _simple("Tan"),
    "arccos": _simple("Acos"),
    "arcsin": _simple("Asin"),
    "arctan": _simple("Atan"),
    "broadcast_equal": _simple("Equal"),
    "broadcast_greater": _simple("Greater"),
    "broadcast_lesser": _simple("Less"),
    "prod": _tr_reduce("ReduceProd"),
    "argmax": _tr_arg_reduce("ArgMax"),
    "argmin": _tr_arg_reduce("ArgMin"),
    "hard_sigmoid": _simple(
        "HardSigmoid", lambda p: [_attr_f("alpha", p.get("alpha", 0.2)),
                                  _attr_f("beta", p.get("beta", 0.5))]),
    "depth_to_space": _simple(
        "DepthToSpace", lambda p: [_attr_i("blocksize", p["block_size"])]),
    "space_to_depth": _simple(
        "SpaceToDepth", lambda p: [_attr_i("blocksize", p["block_size"])]),
    "slice_axis": _tr_slice_axis,
    "SliceChannel": _tr_split,
    "split": _tr_split,
    "Pad": _tr_pad,
    "pad": _tr_pad,
    "LRN": _simple(
        "LRN", lambda p: [_attr_i("size", p["nsize"]),
                          _attr_f("alpha", p.get("alpha", 1e-4)),
                          _attr_f("beta", p.get("beta", 0.75)),
                          _attr_f("bias", p.get("knorm", 2.0))]),
    "L2Normalization": _tr_l2norm,
}
_TRANSLATIONS["Cast"] = _TRANSLATIONS["cast"]


def export_model(sym, params, input_shape, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Serialize (sym, params) to ``onnx_file_path`` (reference
    contrib/onnx/mx2onnx/export_model.py:32).  ``input_shape`` is a list
    of shapes, one per data input."""
    if not isinstance(input_shape, (list, tuple)):
        raise TypeError("input_shape must be a list of shapes")
    if input_shape and isinstance(input_shape[0], int):
        input_shape = [tuple(input_shape)]
    model = _Exporter(sym, params).run(list(input_shape), input_type)
    with open(onnx_file_path, "wb") as f:
        f.write(model.encode())
    if verbose:
        import logging
        logging.info("exported ONNX model to %s", onnx_file_path)
    return onnx_file_path
