"""ONNX -> mxnet_tpu graph importer (parity: python/mxnet/contrib/onnx/
onnx2mx/import_model.py + import_onnx.py GraphProto._convert_operator).

Builds a Symbol + arg/aux params from a serialized ModelProto.  Covers
the operator subset the reference's importer exercises for CNN/MLP
models; unsupported ops raise with the op name so gaps are loud.
"""
import numpy as _np

from . import _proto as P
from ...symbol.symbol import Variable, Group, invoke_sym
from ... import ndarray as _nd
from ...base import MXNetError

_DTYPES = {
    P.TensorProto.FLOAT: _np.float32,
    P.TensorProto.UINT8: _np.uint8,
    P.TensorProto.INT8: _np.int8,
    P.TensorProto.INT32: _np.int32,
    P.TensorProto.INT64: _np.int64,
    P.TensorProto.BOOL: _np.bool_,
    P.TensorProto.FLOAT16: _np.float16,
    P.TensorProto.DOUBLE: _np.float64,
}


def tensor_to_numpy(t):
    """TensorProto -> numpy (raw_data or the typed repeated fields)."""
    if t.data_type not in _DTYPES:
        raise MXNetError("unsupported ONNX tensor dtype %d" % t.data_type)
    dtype = _DTYPES[t.data_type]
    shape = tuple(t.dims)
    if t.raw_data:
        arr = _np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = _np.asarray(t.float_data, dtype=dtype)
    elif t.int64_data:
        arr = _np.asarray(t.int64_data, dtype=dtype)
    elif t.int32_data:
        if t.data_type == P.TensorProto.FLOAT16:
            # the spec stores fp16 in int32_data as raw uint16 bits
            arr = _np.asarray(t.int32_data,
                              _np.uint16).view(_np.float16)
        else:
            arr = _np.asarray(t.int32_data, dtype=dtype)
    else:
        arr = _np.zeros(int(_np.prod(shape)) if shape else 0, dtype=dtype)
    return arr.reshape(shape)


def _attrs(node):
    """AttributeProto list -> python dict."""
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode("utf-8")
        elif a.type == P.AttributeProto.TENSOR:
            out[a.name] = tensor_to_numpy(a.t)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == P.AttributeProto.STRINGS:
            out[a.name] = tuple(s.decode("utf-8") for s in a.strings)
        else:
            raise MXNetError("unsupported ONNX attribute type %d (%s)"
                             % (a.type, a.name))
    return out


class _Importer:
    def __init__(self, graph, for_training=False):
        self.graph = graph
        self.params = {n.name: tensor_to_numpy(n) for n in graph.initializer}
        self.syms = {}        # onnx value name -> Symbol
        self.aux_names = set()
        self.used_params = set()
        self._for_training = for_training

    def run(self):
        for vi in self.graph.input:
            if vi.name not in self.params:
                self.syms[vi.name] = Variable(vi.name)
        for node in self.graph.node:
            self._convert(node)
        outs = [self.syms[o.name] for o in self.graph.output]
        sym = outs[0] if len(outs) == 1 else Group(outs)
        args = set(sym.list_arguments())
        aux = set(sym.list_auxiliary_states())
        arg_params = {k: _nd.array(v) for k, v in self.params.items()
                      if k in args}
        aux_params = {k: _nd.array(v) for k, v in self.params.items()
                      if k in aux}
        return sym, arg_params, aux_params

    # -- helpers -----------------------------------------------------------
    def _in(self, node, i):
        """Symbol for input slot i (params become Variables on first use)."""
        name = node.input[i]
        if name == "":
            return None
        if name not in self.syms:
            if name not in self.params:
                raise MXNetError("ONNX graph references unknown value %r"
                                 % name)
            # carry the initializer's shape so bind-time shape inference
            # doesn't depend on an op-specific hook
            self.syms[name] = Variable(name, shape=self.params[name].shape)
        return self.syms[name]

    def _const(self, node, i, kind="ints"):
        """Static value of input i, which must come from an initializer
        (data-dependent shapes can't trace into XLA)."""
        name = node.input[i]
        if name not in self.params:
            raise MXNetError(
                "ONNX %s requires a constant (initializer) input %r — "
                "data-dependent values are unsupported on the jit path"
                % (node.op_type, name))
        self.used_params.add(name)
        v = self.params[name]
        return tuple(int(x) for x in v.reshape(-1)) if kind == "ints" else v

    def _out(self, node, sym):
        for i, out_name in enumerate(node.output):
            self.syms[out_name] = sym[i] if len(node.output) > 1 else sym

    # -- op conversion -----------------------------------------------------
    def _convert(self, node):
        op = node.op_type
        fn = getattr(self, "_cv_" + op, None)
        if fn is None:
            raise MXNetError("ONNX op %r is not supported by the importer"
                             % op)
        fn(node, _attrs(node))

    def _simple(self, node, mx_op, params=None, n_in=None):
        n = len(node.input) if n_in is None else n_in
        ins = [self._in(node, i) for i in range(n)]
        self._out(node, invoke_sym(mx_op, [s for s in ins if s is not None],
                                   params or {}, name=node.name or None))

    # elementwise / unary
    def _cv_Add(self, node, a):
        self._simple(node, "broadcast_add")

    def _cv_Sub(self, node, a):
        self._simple(node, "broadcast_sub")

    def _cv_Mul(self, node, a):
        self._simple(node, "broadcast_mul")

    def _cv_Div(self, node, a):
        self._simple(node, "broadcast_div")

    def _cv_Relu(self, node, a):
        self._simple(node, "Activation", {"act_type": "relu"})

    def _cv_Sigmoid(self, node, a):
        self._simple(node, "sigmoid")

    def _cv_Tanh(self, node, a):
        self._simple(node, "tanh")

    def _cv_Softplus(self, node, a):
        self._simple(node, "Activation", {"act_type": "softrelu"})

    def _cv_Exp(self, node, a):
        self._simple(node, "exp")

    def _cv_Log(self, node, a):
        self._simple(node, "log")

    def _cv_Sqrt(self, node, a):
        self._simple(node, "sqrt")

    def _cv_Neg(self, node, a):
        self._simple(node, "negative")

    def _cv_Abs(self, node, a):
        self._simple(node, "abs")

    def _cv_Pow(self, node, a):
        self._simple(node, "broadcast_power")

    def _cv_Identity(self, node, a):
        self.syms[node.output[0]] = self._in(node, 0)

    def _cv_LeakyRelu(self, node, a):
        self._simple(node, "LeakyReLU",
                     {"act_type": "leaky", "slope": a.get("alpha", 0.01)})

    def _cv_Elu(self, node, a):
        self._simple(node, "LeakyReLU",
                     {"act_type": "elu", "slope": a.get("alpha", 1.0)})

    def _cv_PRelu(self, node, a):
        self._simple(node, "LeakyReLU", {"act_type": "prelu"})

    def _cv_Clip(self, node, a):
        lo, hi = a.get("min"), a.get("max")
        if lo is None and len(node.input) > 1 and node.input[1]:
            lo = float(self._const(node, 1, kind="array").reshape(()))
        if hi is None and len(node.input) > 2 and node.input[2]:
            hi = float(self._const(node, 2, kind="array").reshape(()))
        # both bounds are optional in ONNX (one-sided clips, e.g. ReLU6)
        lo = -3.4028234663852886e38 if lo is None else float(lo)
        hi = 3.4028234663852886e38 if hi is None else float(hi)
        self._simple(node, "clip", {"a_min": lo, "a_max": hi}, n_in=1)

    def _cv_Softmax(self, node, a):
        self._simple(node, "softmax", {"axis": a.get("axis", -1)})

    def _cv_Constant(self, node, a):
        value = a.get("value")
        if value is None:
            raise MXNetError("Constant node without a tensor value")
        self.params[node.output[0]] = value
        self.syms[node.output[0]] = Variable(node.output[0],
                                             shape=value.shape)

    # structure
    def _cv_Flatten(self, node, a):
        axis = a.get("axis", 1)
        if axis != 1:
            raise MXNetError("Flatten axis != 1 unsupported")
        self._simple(node, "Flatten")

    def _cv_Reshape(self, node, a):
        shape = a.get("shape")  # opset < 5 kept it as an attribute
        if shape is None:
            shape = self._const(node, 1)
        self._simple(node, "Reshape", {"shape": tuple(shape)}, n_in=1)

    def _cv_Transpose(self, node, a):
        self._simple(node, "transpose", {"axes": tuple(a.get("perm", ()))})

    def _cv_Concat(self, node, a):
        ins = [self._in(node, i) for i in range(len(node.input))]
        self._out(node, invoke_sym(
            "Concat", ins,
            {"num_args": len(ins), "dim": a.get("axis", 1)},
            name=node.name or None))

    def _cv_Squeeze(self, node, a):
        axes = a.get("axes")
        if axes is None and len(node.input) > 1:
            axes = self._const(node, 1)
        # no axes at all is valid ONNX: squeeze every size-1 dim
        params = {"axis": tuple(axes)} if axes else {}
        self._simple(node, "squeeze", params, n_in=1)

    def _cv_Unsqueeze(self, node, a):
        axes = a.get("axes")
        if axes is None and len(node.input) > 1:
            axes = self._const(node, 1)
        s = self._in(node, 0)
        for ax in sorted(axes):
            s = invoke_sym("expand_dims", [s], {"axis": int(ax)})
        self.syms[node.output[0]] = s

    def _cv_Dropout(self, node, a):
        self._simple(node, "Dropout", {"p": a.get("ratio", 0.5)}, n_in=1)

    def _cv_Cast(self, node, a):
        to = _DTYPES.get(a.get("to"))
        if to is None:
            raise MXNetError("Cast to unsupported dtype %r" % a.get("to"))
        self._simple(node, "cast", {"dtype": _np.dtype(to).name})

    # reductions
    def _reduce(self, node, a, mx_op):
        axes = a.get("axes")
        self._simple(node, mx_op,
                     {"axis": tuple(axes) if axes else None,
                      "keepdims": bool(a.get("keepdims", 1))}, n_in=1)

    def _cv_ReduceMean(self, node, a):
        self._reduce(node, a, "mean")

    def _cv_ReduceSum(self, node, a):
        self._reduce(node, a, "sum")

    def _cv_ReduceMax(self, node, a):
        self._reduce(node, a, "max")

    def _cv_ReduceMin(self, node, a):
        self._reduce(node, a, "min")

    # linear algebra
    def _cv_MatMul(self, node, a):
        self._simple(node, "dot")

    def _cv_Gemm(self, node, a):
        alpha = a.get("alpha", 1.0)
        beta = a.get("beta", 1.0)
        if alpha != 1.0 or beta != 1.0:
            raise MXNetError("Gemm with alpha/beta != 1 unsupported")
        trans_a = a.get("transA", 0)
        trans_b = a.get("transB", 0)
        x = self._in(node, 0)
        w = self._in(node, 1)
        b = self._in(node, 2) if len(node.input) > 2 else None
        if trans_a:
            x = invoke_sym("transpose", [x], {"axes": (1, 0)})
        w_name = node.input[1]
        if trans_b and w_name in self.params:
            # FullyConnected expects (out, in) — ONNX transB=1 matches
            num_hidden = self.params[w_name].shape[0]
            ins = [x, w] + ([b] if b is not None else [])
            self._out(node, invoke_sym(
                "FullyConnected", ins,
                {"num_hidden": num_hidden, "no_bias": b is None},
                name=node.name or None))
            return
        if trans_b:
            w = invoke_sym("transpose", [w], {"axes": (1, 0)})
        y = invoke_sym("dot", [x, w], {})
        if b is not None:
            y = invoke_sym("broadcast_add", [y, b], {})
        self.syms[node.output[0]] = y

    # NN layers
    def _cv_Conv(self, node, a):
        kernel = tuple(a.get("kernel_shape", ()))
        pads = tuple(a.get("pads", (0,) * (2 * len(kernel))))
        n = len(kernel)
        if pads[:n] != pads[n:]:
            raise MXNetError("asymmetric Conv pads unsupported")
        w_name = node.input[1]
        if w_name not in self.params:
            raise MXNetError("Conv weight must be an initializer")
        num_filter = self.params[w_name].shape[0]
        params = {
            "kernel": kernel,
            "stride": tuple(a.get("strides", (1,) * n)),
            "dilate": tuple(a.get("dilations", (1,) * n)),
            "pad": pads[:n],
            "num_filter": num_filter,
            "num_group": a.get("group", 1),
            "no_bias": len(node.input) < 3 or node.input[2] == "",
        }
        self._simple(node, "Convolution", params)

    def _cv_MaxPool(self, node, a):
        self._pool(node, a, "max")

    def _cv_AveragePool(self, node, a):
        self._pool(node, a, "avg")

    def _pool(self, node, a, pool_type):
        kernel = tuple(a.get("kernel_shape", ()))
        n = len(kernel)
        pads = tuple(a.get("pads", (0,) * (2 * n)))
        if pads[:n] != pads[n:]:
            raise MXNetError("asymmetric pool pads unsupported")
        count_include_pad = a.get("count_include_pad", 0)
        self._simple(node, "Pooling", {
            "kernel": kernel, "pool_type": pool_type,
            "stride": tuple(a.get("strides", (1,) * n)),
            "pad": pads[:n],
            "count_include_pad": bool(count_include_pad)}, n_in=1)

    def _cv_GlobalAveragePool(self, node, a):
        self._simple(node, "Pooling",
                     {"pool_type": "avg", "global_pool": True, "kernel": ()})

    def _cv_GlobalMaxPool(self, node, a):
        self._simple(node, "Pooling",
                     {"pool_type": "max", "global_pool": True, "kernel": ()})

    def _cv_BatchNormalization(self, node, a):
        self._simple(node, "BatchNorm", {
            "eps": a.get("epsilon", 1e-5),
            "momentum": a.get("momentum", 0.9),
            "fix_gamma": False,
            # use_global_stats pins inference to the imported running
            # stats (the ONNX norm). For fine-tuning, import with
            # import_model(..., for_training=True): batch stats are used
            # in training mode and the running stats keep updating — the
            # reference importer's semantics.
            "use_global_stats": not self._for_training}, n_in=5)


def import_model(model_file, for_training=False):
    """Read a .onnx file -> (sym, arg_params, aux_params) (reference
    contrib/onnx/onnx2mx/import_model.py:21).

    for_training=False (default) builds an inference graph: BatchNorm is
    pinned to the imported running stats. for_training=True leaves
    training semantics intact so the imported model can be fine-tuned."""
    with open(model_file, "rb") as f:
        data = f.read()
    model = P.ModelProto.decode(data)
    if model.graph is None:
        raise MXNetError("%s contains no graph" % model_file)
    return _Importer(model.graph, for_training=for_training).run()


def get_model_metadata(model_file):
    """Shapes of graph inputs/outputs (reference import_model.py:60)."""
    with open(model_file, "rb") as f:
        model = P.ModelProto.decode(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def _shape(vi):
        tt = vi.type.tensor_type if vi.type else None
        if tt is None or tt.shape is None:
            return (vi.name, None)
        return (vi.name, tuple(d.dim_value for d in tt.shape.dim))

    return {
        "input_tensor_data": [_shape(vi) for vi in g.input
                              if vi.name not in inits],
        "output_tensor_data": [_shape(vi) for vi in g.output],
    }
