"""ONNX -> mxnet_tpu graph importer (parity: python/mxnet/contrib/onnx/
onnx2mx/import_model.py + import_onnx.py GraphProto._convert_operator).

Builds a Symbol + arg/aux params from a serialized ModelProto.  Covers
the reference importer's full 92-entry op table (onnx2mx/
_import_helper.py:28-117) — enough to import the ONNX files the
reference model zoo exports; unsupported ops raise with the op name so
gaps stay loud.
"""
import numpy as _np

from . import _proto as P
from ...symbol.symbol import Variable, Group, invoke_sym
from ... import ndarray as _nd
from ...base import MXNetError

_DTYPES = {
    P.TensorProto.FLOAT: _np.float32,
    P.TensorProto.UINT8: _np.uint8,
    P.TensorProto.INT8: _np.int8,
    P.TensorProto.INT32: _np.int32,
    P.TensorProto.INT64: _np.int64,
    P.TensorProto.BOOL: _np.bool_,
    P.TensorProto.FLOAT16: _np.float16,
    P.TensorProto.DOUBLE: _np.float64,
}


def tensor_to_numpy(t):
    """TensorProto -> numpy (raw_data or the typed repeated fields)."""
    if t.data_type not in _DTYPES:
        raise MXNetError("unsupported ONNX tensor dtype %d" % t.data_type)
    dtype = _DTYPES[t.data_type]
    shape = tuple(t.dims)
    if t.raw_data:
        arr = _np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = _np.asarray(t.float_data, dtype=dtype)
    elif t.int64_data:
        arr = _np.asarray(t.int64_data, dtype=dtype)
    elif t.int32_data:
        if t.data_type == P.TensorProto.FLOAT16:
            # the spec stores fp16 in int32_data as raw uint16 bits
            arr = _np.asarray(t.int32_data,
                              _np.uint16).view(_np.float16)
        else:
            arr = _np.asarray(t.int32_data, dtype=dtype)
    else:
        arr = _np.zeros(int(_np.prod(shape)) if shape else 0, dtype=dtype)
    return arr.reshape(shape)


def _attrs(node):
    """AttributeProto list -> python dict."""
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode("utf-8")
        elif a.type == P.AttributeProto.TENSOR:
            out[a.name] = tensor_to_numpy(a.t)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == P.AttributeProto.STRINGS:
            out[a.name] = tuple(s.decode("utf-8") for s in a.strings)
        else:
            raise MXNetError("unsupported ONNX attribute type %d (%s)"
                             % (a.type, a.name))
    return out


class _Importer:
    def __init__(self, graph, for_training=False, opset=9):
        self.graph = graph
        self.opset = opset
        self.params = {n.name: tensor_to_numpy(n) for n in graph.initializer}
        self.syms = {}        # onnx value name -> Symbol
        self.aux_names = set()
        self.used_params = set()
        self._for_training = for_training

    def run(self):
        for vi in self.graph.input:
            if vi.name not in self.params:
                self.syms[vi.name] = Variable(vi.name)
        for node in self.graph.node:
            self._convert(node)
        outs = [self.syms[o.name] for o in self.graph.output]
        sym = outs[0] if len(outs) == 1 else Group(outs)
        args = set(sym.list_arguments())
        aux = set(sym.list_auxiliary_states())
        arg_params = {k: _nd.array(v) for k, v in self.params.items()
                      if k in args}
        aux_params = {k: _nd.array(v) for k, v in self.params.items()
                      if k in aux}
        return sym, arg_params, aux_params

    # -- helpers -----------------------------------------------------------
    def _in(self, node, i):
        """Symbol for input slot i (params become Variables on first use)."""
        name = node.input[i]
        if name == "":
            return None
        if name not in self.syms:
            if name not in self.params:
                raise MXNetError("ONNX graph references unknown value %r"
                                 % name)
            # carry the initializer's shape so bind-time shape inference
            # doesn't depend on an op-specific hook
            self.syms[name] = Variable(name, shape=self.params[name].shape)
        return self.syms[name]

    def _const(self, node, i, kind="ints"):
        """Static value of input i, which must come from an initializer
        (data-dependent shapes can't trace into XLA)."""
        name = node.input[i]
        if name not in self.params:
            raise MXNetError(
                "ONNX %s requires a constant (initializer) input %r — "
                "data-dependent values are unsupported on the jit path"
                % (node.op_type, name))
        self.used_params.add(name)
        v = self.params[name]
        return tuple(int(x) for x in v.reshape(-1)) if kind == "ints" else v

    def _out(self, node, sym):
        for i, out_name in enumerate(node.output):
            self.syms[out_name] = sym[i] if len(node.output) > 1 else sym

    # -- op conversion -----------------------------------------------------
    def _convert(self, node):
        op = node.op_type
        fn = getattr(self, "_cv_" + op, None)
        if fn is None:
            raise MXNetError("ONNX op %r is not supported by the importer"
                             % op)
        fn(node, _attrs(node))

    def _simple(self, node, mx_op, params=None, n_in=None):
        n = len(node.input) if n_in is None else n_in
        ins = [self._in(node, i) for i in range(n)]
        self._out(node, invoke_sym(mx_op, [s for s in ins if s is not None],
                                   params or {}, name=node.name or None))

    # elementwise / unary
    def _cv_Add(self, node, a):
        self._simple(node, "broadcast_add")

    def _cv_Sub(self, node, a):
        self._simple(node, "broadcast_sub")

    def _cv_Mul(self, node, a):
        self._simple(node, "broadcast_mul")

    def _cv_Div(self, node, a):
        self._simple(node, "broadcast_div")

    def _cv_Relu(self, node, a):
        self._simple(node, "Activation", {"act_type": "relu"})

    def _cv_Sigmoid(self, node, a):
        self._simple(node, "sigmoid")

    def _cv_Tanh(self, node, a):
        self._simple(node, "tanh")

    def _cv_Softplus(self, node, a):
        self._simple(node, "Activation", {"act_type": "softrelu"})

    def _cv_Exp(self, node, a):
        self._simple(node, "exp")

    def _cv_Log(self, node, a):
        self._simple(node, "log")

    def _cv_Sqrt(self, node, a):
        self._simple(node, "sqrt")

    def _cv_Neg(self, node, a):
        self._simple(node, "negative")

    def _cv_Abs(self, node, a):
        self._simple(node, "abs")

    def _cv_Pow(self, node, a):
        self._simple(node, "broadcast_power")

    def _cv_Identity(self, node, a):
        self.syms[node.output[0]] = self._in(node, 0)

    def _cv_LeakyRelu(self, node, a):
        self._simple(node, "LeakyReLU",
                     {"act_type": "leaky", "slope": a.get("alpha", 0.01)})

    def _cv_Elu(self, node, a):
        self._simple(node, "LeakyReLU",
                     {"act_type": "elu", "slope": a.get("alpha", 1.0)})

    def _cv_PRelu(self, node, a):
        self._simple(node, "LeakyReLU", {"act_type": "prelu"})

    def _cv_Clip(self, node, a):
        lo, hi = a.get("min"), a.get("max")
        if lo is None and len(node.input) > 1 and node.input[1]:
            lo = float(self._const(node, 1, kind="array").reshape(()))
        if hi is None and len(node.input) > 2 and node.input[2]:
            hi = float(self._const(node, 2, kind="array").reshape(()))
        # both bounds are optional in ONNX (one-sided clips, e.g. ReLU6)
        lo = -3.4028234663852886e38 if lo is None else float(lo)
        hi = 3.4028234663852886e38 if hi is None else float(hi)
        self._simple(node, "clip", {"a_min": lo, "a_max": hi}, n_in=1)

    def _softmax_axis(self, a):
        # opset < 13: default axis=1 with flatten-to-2D semantics (the
        # common case — a 2D classifier head — is exact; reference
        # importer also passes axis=1). opset >= 13: per-axis, default -1.
        return a.get("axis", 1 if self.opset < 13 else -1)

    def _cv_Softmax(self, node, a):
        self._simple(node, "softmax", {"axis": self._softmax_axis(a)})

    def _cv_Constant(self, node, a):
        value = a.get("value")
        if value is None:
            raise MXNetError("Constant node without a tensor value")
        self.params[node.output[0]] = value
        self.syms[node.output[0]] = Variable(node.output[0],
                                             shape=value.shape)

    # structure
    def _cv_Flatten(self, node, a):
        axis = a.get("axis", 1)
        if axis != 1:
            raise MXNetError("Flatten axis != 1 unsupported")
        self._simple(node, "Flatten")

    def _cv_Reshape(self, node, a):
        shape = a.get("shape")  # opset < 5 kept it as an attribute
        if shape is None:
            shape = self._const(node, 1)
        self._simple(node, "Reshape", {"shape": tuple(shape)}, n_in=1)

    def _cv_Transpose(self, node, a):
        self._simple(node, "transpose", {"axes": tuple(a.get("perm", ()))})

    def _cv_Concat(self, node, a):
        ins = [self._in(node, i) for i in range(len(node.input))]
        self._out(node, invoke_sym(
            "Concat", ins,
            {"num_args": len(ins), "dim": a.get("axis", 1)},
            name=node.name or None))

    def _cv_Squeeze(self, node, a):
        axes = a.get("axes")
        if axes is None and len(node.input) > 1:
            axes = self._const(node, 1)
        # no axes at all is valid ONNX: squeeze every size-1 dim
        params = {"axis": tuple(axes)} if axes else {}
        self._simple(node, "squeeze", params, n_in=1)

    def _cv_Unsqueeze(self, node, a):
        axes = a.get("axes")
        if axes is None and len(node.input) > 1:
            axes = self._const(node, 1)
        s = self._in(node, 0)
        for ax in sorted(axes):
            s = invoke_sym("expand_dims", [s], {"axis": int(ax)})
        self.syms[node.output[0]] = s

    def _cv_Dropout(self, node, a):
        self._simple(node, "Dropout", {"p": a.get("ratio", 0.5)}, n_in=1)

    def _cv_Cast(self, node, a):
        to = _DTYPES.get(a.get("to"))
        if to is None:
            raise MXNetError("Cast to unsupported dtype %r" % a.get("to"))
        self._simple(node, "cast", {"dtype": _np.dtype(to).name})

    # reductions
    def _reduce(self, node, a, mx_op):
        axes = a.get("axes")
        self._simple(node, mx_op,
                     {"axis": tuple(axes) if axes else None,
                      "keepdims": bool(a.get("keepdims", 1))}, n_in=1)

    def _cv_ReduceMean(self, node, a):
        self._reduce(node, a, "mean")

    def _cv_ReduceSum(self, node, a):
        self._reduce(node, a, "sum")

    def _cv_ReduceMax(self, node, a):
        self._reduce(node, a, "max")

    def _cv_ReduceMin(self, node, a):
        self._reduce(node, a, "min")

    # linear algebra
    def _cv_MatMul(self, node, a):
        self._simple(node, "dot")

    def _cv_Gemm(self, node, a):
        alpha = a.get("alpha", 1.0)
        beta = a.get("beta", 1.0)
        if alpha != 1.0 or beta != 1.0:
            raise MXNetError("Gemm with alpha/beta != 1 unsupported")
        trans_a = a.get("transA", 0)
        trans_b = a.get("transB", 0)
        x = self._in(node, 0)
        w = self._in(node, 1)
        b = self._in(node, 2) if len(node.input) > 2 else None
        if trans_a:
            x = invoke_sym("transpose", [x], {"axes": (1, 0)})
        w_name = node.input[1]
        if trans_b and w_name in self.params:
            # FullyConnected expects (out, in) — ONNX transB=1 matches
            num_hidden = self.params[w_name].shape[0]
            ins = [x, w] + ([b] if b is not None else [])
            self._out(node, invoke_sym(
                "FullyConnected", ins,
                {"num_hidden": num_hidden, "no_bias": b is None},
                name=node.name or None))
            return
        if trans_b:
            w = invoke_sym("transpose", [w], {"axes": (1, 0)})
        y = invoke_sym("dot", [x, w], {})
        if b is not None:
            y = invoke_sym("broadcast_add", [y, b], {})
        self.syms[node.output[0]] = y

    # NN layers
    def _resolve_pads(self, a, kernel, op_name):
        """ONNX pads/auto_pad -> symmetric per-axis pads. auto_pad=SAME
        needs runtime spatial dims for stride>1 or even kernels, which a
        shape-less import can't provide — those fail loudly instead of
        silently zero-padding (the bug this replaces)."""
        n = len(kernel)
        auto = a.get("auto_pad", "NOTSET")
        if auto in ("NOTSET", "", "VALID"):
            pads = tuple(a.get("pads", (0,) * (2 * n))) \
                if auto in ("NOTSET", "") else (0,) * (2 * n)
            if pads[:n] != pads[n:]:
                raise MXNetError("asymmetric %s pads unsupported"
                                 % op_name)
            return pads[:n]
        if auto in ("SAME_UPPER", "SAME_LOWER"):
            strides = tuple(a.get("strides", (1,) * n))
            dilations = tuple(a.get("dilations", (1,) * n))
            # effective (dilated) kernel decides SAME padding
            eff = tuple(d * (k - 1) + 1 for k, d in zip(kernel, dilations))
            if any(s != 1 for s in strides) or any(e % 2 == 0
                                                   for e in eff):
                raise MXNetError(
                    "%s auto_pad=%s with stride>1 or even effective "
                    "kernel needs runtime shapes; re-export with "
                    "explicit pads" % (op_name, auto))
            return tuple((e - 1) // 2 for e in eff)
        raise MXNetError("%s auto_pad=%r unsupported" % (op_name, auto))

    def _cv_Conv(self, node, a):
        kernel = tuple(a.get("kernel_shape", ()))
        n = len(kernel)
        pads = self._resolve_pads(a, kernel, "Conv")
        w_name = node.input[1]
        if w_name not in self.params:
            raise MXNetError("Conv weight must be an initializer")
        num_filter = self.params[w_name].shape[0]
        params = {
            "kernel": kernel,
            "stride": tuple(a.get("strides", (1,) * n)),
            "dilate": tuple(a.get("dilations", (1,) * n)),
            "pad": pads,
            "num_filter": num_filter,
            "num_group": a.get("group", 1),
            "no_bias": len(node.input) < 3 or node.input[2] == "",
        }
        self._simple(node, "Convolution", params)

    def _cv_MaxPool(self, node, a):
        self._pool(node, a, "max")

    def _cv_AveragePool(self, node, a):
        self._pool(node, a, "avg")

    def _pool(self, node, a, pool_type):
        kernel = tuple(a.get("kernel_shape", ()))
        pads = self._resolve_pads(a, kernel, node.op_type)
        count_include_pad = a.get("count_include_pad", 0)
        self._simple(node, "Pooling", {
            "kernel": kernel, "pool_type": pool_type,
            "stride": tuple(a.get("strides", (1,) * len(kernel))),
            "pad": pads,
            # opset>=10 ceil_mode == the reference's "full" convention
            "pooling_convention": "full" if a.get("ceil_mode") else "valid",
            "count_include_pad": bool(count_include_pad)}, n_in=1)

    def _cv_GlobalAveragePool(self, node, a):
        self._simple(node, "Pooling",
                     {"pool_type": "avg", "global_pool": True, "kernel": ()})

    def _cv_GlobalMaxPool(self, node, a):
        self._simple(node, "Pooling",
                     {"pool_type": "max", "global_pool": True, "kernel": ()})

    def _cv_BatchNormalization(self, node, a):
        self._simple(node, "BatchNorm", {
            "eps": a.get("epsilon", 1e-5),
            "momentum": a.get("momentum", 0.9),
            "fix_gamma": False,
            # use_global_stats pins inference to the imported running
            # stats (the ONNX norm). For fine-tuning, import with
            # import_model(..., for_training=True): batch stats are used
            # in training mode and the running stats keep updating — the
            # reference importer's semantics.
            "use_global_stats": not self._for_training}, n_in=5)

    _cv_SpatialBN = _cv_BatchNormalization  # legacy caffe2 name (reference
    # _import_helper.py maps both to batch_norm)

    # -- remainder of the reference's 92-entry import table ----------------
    # (reference onnx2mx/_import_helper.py:28-117; each converter mirrors
    # the matching _op_translations.py translation, re-targeted at our op
    # registry)

    def _cv_Ceil(self, node, a):
        self._simple(node, "ceil")

    def _cv_Floor(self, node, a):
        self._simple(node, "floor")

    def _cv_Reciprocal(self, node, a):
        self._simple(node, "reciprocal")

    def _cv_Softsign(self, node, a):
        self._simple(node, "softsign")

    def _cv_LogSoftmax(self, node, a):
        self._simple(node, "log_softmax", {"axis": self._softmax_axis(a)})

    def _cv_Selu(self, node, a):
        self._simple(node, "LeakyReLU", {"act_type": "selu"})

    def _cv_HardSigmoid(self, node, a):
        self._simple(node, "hard_sigmoid",
                     {"alpha": a.get("alpha", 0.2),
                      "beta": a.get("beta", 0.5)})

    def _cv_Cos(self, node, a):
        self._simple(node, "cos")

    def _cv_Sin(self, node, a):
        self._simple(node, "sin")

    def _cv_Tan(self, node, a):
        self._simple(node, "tan")

    def _cv_Acos(self, node, a):
        self._simple(node, "arccos")

    def _cv_Asin(self, node, a):
        self._simple(node, "arcsin")

    def _cv_Atan(self, node, a):
        self._simple(node, "arctan")

    # comparison / logical (ONNX outputs bool; our broadcast_* comparisons
    # return the input dtype — downstream Cast/Where handle both)
    def _cv_Less(self, node, a):
        self._simple(node, "broadcast_lesser")

    def _cv_Greater(self, node, a):
        self._simple(node, "broadcast_greater")

    def _cv_Equal(self, node, a):
        self._simple(node, "broadcast_equal")

    def _cv_And(self, node, a):
        self._simple(node, "broadcast_logical_and")

    def _cv_Or(self, node, a):
        self._simple(node, "broadcast_logical_or")

    def _cv_Xor(self, node, a):
        self._simple(node, "broadcast_logical_xor")

    def _cv_Not(self, node, a):
        self._simple(node, "logical_not")

    # variadic elementwise
    def _cv_Sum(self, node, a):
        self._simple(node, "add_n")

    def _cv_Mean(self, node, a):
        n = len(node.input)
        s = invoke_sym("add_n", [self._in(node, i) for i in range(n)], {})
        self.syms[node.output[0]] = invoke_sym(
            "_div_scalar", [s], {"scalar": float(n)})

    def _fold_binary(self, node, mx_op):
        acc = self._in(node, 0)
        for i in range(1, len(node.input)):
            acc = invoke_sym(mx_op, [acc, self._in(node, i)], {})
        self.syms[node.output[0]] = acc

    def _cv_Max(self, node, a):
        self._fold_binary(node, "broadcast_maximum")

    def _cv_Min(self, node, a):
        self._fold_binary(node, "broadcast_minimum")

    # reductions (beyond Mean/Sum/Max/Min)
    def _cv_ReduceProd(self, node, a):
        self._reduce(node, a, "prod")

    def _composed_reduce(self, node, a, inner, outer):
        """outer(reduce_sum(inner(x))) — the ONNX composite reductions."""
        axes = a.get("axes")
        x = self._in(node, 0)
        if inner:
            x = invoke_sym(inner, [x], {})
        x = invoke_sym("sum", [x],
                       {"axis": tuple(axes) if axes else None,
                        "keepdims": bool(a.get("keepdims", 1))})
        if outer:
            x = invoke_sym(outer, [x], {})
        self.syms[node.output[0]] = x

    def _cv_ReduceSumSquare(self, node, a):
        self._composed_reduce(node, a, "square", None)

    def _cv_ReduceLogSum(self, node, a):
        self._composed_reduce(node, a, None, "log")

    def _cv_ReduceL1(self, node, a):
        self._composed_reduce(node, a, "abs", None)

    def _cv_ReduceL2(self, node, a):
        self._composed_reduce(node, a, "square", "sqrt")

    def _cv_ReduceLogSumExp(self, node, a):
        self._composed_reduce(node, a, "exp", "log")

    def _cv_ArgMax(self, node, a):
        self._simple(node, "argmax",
                     {"axis": a.get("axis", 0),
                      "keepdims": bool(a.get("keepdims", 1))})

    def _cv_ArgMin(self, node, a):
        self._simple(node, "argmin",
                     {"axis": a.get("axis", 0),
                      "keepdims": bool(a.get("keepdims", 1))})

    # structure / indexing
    def _cv_Shape(self, node, a):
        self._simple(node, "shape_array")

    def _cv_Gather(self, node, a):
        # mode="wrap": ONNX negative indices count from the end
        self._simple(node, "take", {"axis": a.get("axis", 0),
                                    "mode": "wrap"})

    def _cv_DepthToSpace(self, node, a):
        self._simple(node, "depth_to_space",
                     {"block_size": a["blocksize"]})

    def _cv_SpaceToDepth(self, node, a):
        self._simple(node, "space_to_depth",
                     {"block_size": a["blocksize"]})

    def _cv_Split(self, node, a):
        axis = a.get("axis", 0)
        sizes = a.get("split")
        if sizes is None and len(node.input) > 1:  # opset 13 moved to input
            sizes = self._const(node, 1)
        x = self._in(node, 0)
        if sizes is None or len(set(sizes)) == 1:
            out = invoke_sym("split", [x],
                             {"num_outputs": len(node.output), "axis": axis},
                             name=node.name or None)
            self._out(node, out)
            return
        start = 0
        for i, sz in enumerate(sizes):  # unequal split -> slice_axis chain
            self.syms[node.output[i]] = invoke_sym(
                "slice_axis", [x],
                {"axis": axis, "begin": start, "end": start + int(sz)})
            start += int(sz)

    _INT_HUGE = 2 ** 31 - 1

    def _cv_Slice(self, node, a):
        starts = a.get("starts")
        if starts is not None:  # opset < 10: attributes
            ends = a["ends"]
            axes = a.get("axes", tuple(range(len(starts))))
            steps = (1,) * len(starts)
        else:  # opset >= 10: constant inputs
            starts = self._const(node, 1)
            ends = self._const(node, 2)
            axes = (self._const(node, 3) if len(node.input) > 3
                    and node.input[3] else tuple(range(len(starts))))
            steps = (self._const(node, 4) if len(node.input) > 4
                     and node.input[4] else (1,) * len(starts))
        x = self._in(node, 0)
        for ax, b, e, st in zip(axes, starts, ends, steps):
            if st != 1:
                raise MXNetError("Slice with step != 1 unsupported")
            # INT64_MAX / INT32_MAX end means "to the end of the axis"
            end = None if e >= self._INT_HUGE else int(e)
            x = invoke_sym("slice_axis", [x],
                           {"axis": int(ax), "begin": int(b), "end": end})
        self.syms[node.output[0]] = x

    def _cv_Pad(self, node, a):
        pads = a.get("pads")
        if pads is None and len(node.input) > 1:  # opset >= 11: input
            pads = self._const(node, 1)
        value = a.get("value", 0.0)
        if len(node.input) > 2 and node.input[2]:
            value = float(self._const(node, 2, kind="array").reshape(()))
        mode = a.get("mode", "constant")
        n = len(pads) // 2
        # ONNX: [x1_begin..xn_begin, x1_end..xn_end] -> flat (b,a) per axis
        pw = []
        for i in range(n):
            pw += [int(pads[i]), int(pads[i + n])]
        self._simple(node, "pad",
                     {"mode": mode, "pad_width": tuple(pw),
                      "constant_value": value}, n_in=1)

    # NN layers
    def _cv_ConvTranspose(self, node, a):
        kernel = tuple(a.get("kernel_shape", ()))
        n = len(kernel)
        out_shape = a.get("output_shape")
        if a.get("auto_pad", "NOTSET") not in ("NOTSET", "") \
                and out_shape is None:
            # SAME/VALID deconvolution padding depends on runtime shapes
            raise MXNetError(
                "ConvTranspose auto_pad=%r unsupported; re-export with "
                "explicit pads or output_shape" % a["auto_pad"])
        pads = tuple(a.get("pads", (0,) * (2 * n)))
        if pads[:n] != pads[n:] and out_shape is None:
            raise MXNetError("asymmetric ConvTranspose pads unsupported")
        w_name = node.input[1]
        if w_name not in self.params:
            raise MXNetError("ConvTranspose weight must be an initializer")
        group = a.get("group", 1)
        # ONNX weight layout (C_in, C_out/group, *kernel) == our
        # Deconvolution convention (ops/nn.py deconvolution)
        num_filter = self.params[w_name].shape[1] * group
        params = {
            "kernel": kernel,
            "stride": tuple(a.get("strides", (1,) * n)),
            "dilate": tuple(a.get("dilations", (1,) * n)),
            "num_filter": num_filter, "num_group": group,
            "no_bias": len(node.input) < 3 or node.input[2] == ""}
        if out_shape is not None:
            # output_shape overrides pads: Deconvolution's target_shape
            # runs the reference InferPad (pad/adj derived, possibly
            # asymmetric-equivalent), matching ONNX auto-pad semantics
            params["target_shape"] = tuple(out_shape[-n:])
        else:
            params["pad"] = pads[:n]
            params["adj"] = tuple(a.get("output_padding", (0,) * n))
        self._simple(node, "Deconvolution", params)

    def _cv_FC(self, node, a):
        """Legacy caffe2 FC (reference maps it to fully_connected)."""
        w_name = node.input[1]
        if w_name not in self.params:
            raise MXNetError("FC weight must be an initializer")
        self._simple(node, "FullyConnected",
                     {"num_hidden": self.params[w_name].shape[0],
                      "no_bias": len(node.input) < 3})

    def _cv_LpNormalization(self, node, a):
        # beyond the reference's 92-entry table: round-trips our own
        # exporter's L2Normalization channel-mode output
        if a.get("p", 2) != 2 or a.get("axis", -1) != 1:
            raise MXNetError("LpNormalization only imports as p=2 axis=1 "
                             "(channel-mode L2Normalization)")
        self._simple(node, "L2Normalization", {"mode": "channel"})

    def _cv_LRN(self, node, a):
        self._simple(node, "LRN", {
            "nsize": a["size"], "alpha": a.get("alpha", 1e-4),
            "beta": a.get("beta", 0.75), "knorm": a.get("bias", 1.0)})

    def _cv_InstanceNormalization(self, node, a):
        self._simple(node, "InstanceNorm",
                     {"eps": a.get("epsilon", 1e-5)}, n_in=3)

    def _cv_MaxRoiPool(self, node, a):
        self._simple(node, "ROIPooling", {
            "pooled_size": tuple(a["pooled_shape"]),
            "spatial_scale": a.get("spatial_scale", 1.0)})

    def _cv_LpPool(self, node, a):
        kernel = tuple(a.get("kernel_shape", ()))
        n = len(kernel)
        pads = tuple(a.get("pads", (0,) * (2 * n)))
        if pads[:n] != pads[n:]:
            raise MXNetError("asymmetric LpPool pads unsupported")
        self._simple(node, "Pooling", {
            "kernel": kernel, "pool_type": "lp",
            "p_value": a.get("p", 2),
            "stride": tuple(a.get("strides", (1,) * n)),
            "pad": pads[:n]}, n_in=1)

    def _cv_GlobalLpPool(self, node, a):
        self._simple(node, "Pooling",
                     {"pool_type": "lp", "p_value": a.get("p", 2),
                      "global_pool": True, "kernel": ()})

    # random
    def _cv_RandomUniform(self, node, a):
        dt = _DTYPES.get(a.get("dtype", P.TensorProto.FLOAT), _np.float32)
        self.syms[node.output[0]] = invoke_sym(
            "_random_uniform", [],
            {"low": a.get("low", 0.0), "high": a.get("high", 1.0),
             "shape": tuple(a["shape"]), "dtype": _np.dtype(dt).name})

    def _cv_RandomNormal(self, node, a):
        dt = _DTYPES.get(a.get("dtype", P.TensorProto.FLOAT), _np.float32)
        self.syms[node.output[0]] = invoke_sym(
            "_random_normal", [],
            {"loc": a.get("mean", 0.0), "scale": a.get("scale", 1.0),
             "shape": tuple(a["shape"]), "dtype": _np.dtype(dt).name})

    def _like_dtype(self, a):
        if "dtype" not in a:
            return None
        dt = _DTYPES.get(a["dtype"])
        if dt is None:
            raise MXNetError("Random*Like dtype %r unsupported" % a["dtype"])
        return _np.dtype(dt).name

    def _cv_RandomUniformLike(self, node, a):
        self._simple(node, "_random_uniform_like",
                     {"low": a.get("low", 0.0), "high": a.get("high", 1.0),
                      "dtype": self._like_dtype(a)})

    def _cv_RandomNormalLike(self, node, a):
        self._simple(node, "_random_normal_like",
                     {"loc": a.get("mean", 0.0),
                      "scale": a.get("scale", 1.0),
                      "dtype": self._like_dtype(a)})


def import_model(model_file, for_training=False):
    """Read a .onnx file -> (sym, arg_params, aux_params) (reference
    contrib/onnx/onnx2mx/import_model.py:21).

    for_training=False (default) builds an inference graph: BatchNorm is
    pinned to the imported running stats. for_training=True leaves
    training semantics intact so the imported model can be fine-tuned."""
    with open(model_file, "rb") as f:
        data = f.read()
    model = P.ModelProto.decode(data)
    if model.graph is None:
        raise MXNetError("%s contains no graph" % model_file)
    opset = 9
    for osi in model.opset_import:
        if osi.domain in ("", "ai.onnx"):  # both spellings of the
            opset = osi.version            # default ONNX domain
    return _Importer(model.graph, for_training=for_training,
                     opset=opset).run()


def get_model_metadata(model_file):
    """Shapes of graph inputs/outputs (reference import_model.py:60)."""
    with open(model_file, "rb") as f:
        model = P.ModelProto.decode(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def _shape(vi):
        tt = vi.type.tensor_type if vi.type else None
        if tt is None or tt.shape is None:
            return (vi.name, None)
        return (vi.name, tuple(d.dim_value for d in tt.shape.dim))

    return {
        "input_tensor_data": [_shape(vi) for vi in g.input
                              if vi.name not in inits],
        "output_tensor_data": [_shape(vi) for vi in g.output],
    }
