"""TensorBoard logging callback (parity: python/mxnet/contrib/
tensorboard.py:24).

Uses ``tensorboard``'s pure-python ``SummaryWriter`` if available (the
reference wants ``mxboard``, which wraps the same event-file format); if
neither import resolves the callback degrades to a logged error, exactly
like the reference.
"""
import logging


def _make_writer(logging_dir):
    try:
        from mxboard import SummaryWriter           # reference's choice
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from tensorboardX import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError:
        return None


class LogMetricsCallback:
    """Batch/epoch-end callback writing each metric as a TB scalar."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = _make_writer(logging_dir)
        self._step = 0
        if self.summary_writer is None:
            logging.error("no SummaryWriter backend found; install mxboard "
                          "or a tensorboard-compatible writer")

    def __call__(self, param):
        if param.eval_metric is None or self.summary_writer is None:
            return
        # own monotone counter, not param.epoch: as a batch_end_callback
        # every batch of an epoch would otherwise land on the same step and
        # overwrite the previous point
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value,
                                           global_step=self._step)
