"""TensorRT contrib surface (reference contrib/tensorrt.py:30-106).

TensorRT is a CUDA-platform engine; the TPU-native replacement for its
role — ahead-of-time compiled, weights-baked inference artifacts — is
:mod:`mxnet_tpu.serving` (`export_compiled` / `CompiledModel`, the
`.mxtpu` StableHLO format; docs/serving.md). These functions fail
loudly with that pointer instead of pretending a TRT engine exists
(same policy as rtc.py for CUDA runtime compilation).
"""
from ..base import MXNetError

__all__ = ["set_use_tensorrt", "get_use_tensorrt", "get_optimized_symbol",
           "tensorrt_bind"]

_MSG = ("TensorRT is a CUDA-only engine with no TPU analog; use "
        "mxnet_tpu.serving.export_compiled / CompiledModel for "
        "AOT-compiled inference artifacts (docs/serving.md)")


def set_use_tensorrt(status):
    if status:
        raise MXNetError(_MSG)


def get_use_tensorrt():
    return False


def get_optimized_symbol(executor):
    raise MXNetError(_MSG)


def tensorrt_bind(symbol, ctx, all_params, **kwargs):
    raise MXNetError(_MSG)
