"""Stateful RNG facade over JAX's stateless PRNG.

The reference has per-device stateful RNG resources
(``src/resource.cc``, ``ResourceRequest::kRandom``) and a test discipline
built on ``mx.random.seed`` (tests/python/unittest/common.py ``with_seed``).
TPU-native design (SURVEY.md §7 hard-part 5): a *key chain* — a module-level
key that is split on every draw — reproduces the stateful surface, while
traced (jitted) graphs never touch global state: during tracing, draws pull
subkeys from an explicit key argument threaded by the executor, so compiled
functions get fresh randomness per invocation with zero recompilation.
"""
from __future__ import annotations

import threading

import jax
import numpy as _np

# the reference re-exports the nd.random samplers at mx.random level
# (python/mxnet/random.py:26 `from .ndarray.random import *`); resolved
# lazily (PEP 562) because this module loads before the ndarray package
_SAMPLERS = ("uniform", "normal", "randn", "poisson", "exponential",
             "gamma", "negative_binomial", "generalized_negative_binomial",
             "multinomial", "shuffle", "randint")

__all__ = ["seed", "next_key", "get_state", "set_state", "TraceRng",
           "current_trace_rng", *_SAMPLERS]


def __getattr__(name):
    if name in _SAMPLERS:
        from .ndarray import random as _ndrandom
        return getattr(_ndrandom, name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

_state = threading.local()


def _chain():
    if not hasattr(_state, "key"):
        from .config import flags
        if flags.enforce_determinism:
            raise RuntimeError(
                "MXNET_ENFORCE_DETERMINISM is set but mx.random.seed() was "
                "never called on this thread — refusing to auto-seed from "
                "entropy (parity: env_var.md:226 restricts nondeterminism).")
        with jax.ensure_compile_time_eval():
            _state.key = jax.random.PRNGKey(
                _np.random.randint(0, 2**31 - 1))
    return _state.key


def seed(seed_state):
    """Seed the global RNG (parity: mx.random.seed)."""
    _state.key = jax.random.PRNGKey(int(seed_state))
    _np.random.seed(int(seed_state) % (2**32))


def get_state():
    """Snapshot the thread's RNG state for checkpointing.

    Returns ``(jax_key_data, numpy_state)`` where ``jax_key_data`` is a
    plain uint32 array (None when the chain was never seeded/drawn) and
    ``numpy_state`` is ``np.random.get_state()``.  Round-trips through
    ``set_state`` so a resumed run continues the exact key chain.
    """
    key = getattr(_state, "key", None)
    if key is not None:
        try:  # typed (new-style) keys need unwrapping to raw uint32 data
            key = _np.asarray(jax.random.key_data(key))
        except (TypeError, AttributeError):
            key = _np.asarray(key)
    return key, _np.random.get_state()


def set_state(snapshot):
    """Restore a snapshot produced by ``get_state`` (checkpoint resume)."""
    key, np_state = snapshot
    if key is None:
        if hasattr(_state, "key"):
            del _state.key
    else:
        with jax.ensure_compile_time_eval():
            _state.key = jax.numpy.asarray(key, dtype=jax.numpy.uint32)
    if np_state is not None:
        _np.random.set_state(np_state)


class TraceRng:
    """Collects key requests while tracing a graph.

    The executor creates one per trace; each random op calls ``next_key()``
    which folds a fresh per-site subkey out of a single key *input* to the
    compiled function. At run time the executor feeds a new key each call.
    """

    def __init__(self, base_key):
        self.base_key = base_key
        self.count = 0

    def next_key(self):
        k = jax.random.fold_in(self.base_key, self.count)
        self.count += 1
        return k


def current_trace_rng():
    return getattr(_state, "trace_rng", None)


class _trace_scope:
    def __init__(self, rng):
        self.rng = rng

    def __enter__(self):
        self.prev = getattr(_state, "trace_rng", None)
        _state.trace_rng = self.rng
        return self.rng

    def __exit__(self, *a):
        _state.trace_rng = self.prev


def trace_scope(base_key):
    return _trace_scope(TraceRng(base_key))


def next_key():
    """Draw a fresh PRNG key.

    Inside a trace scope: pull from the trace's key input (keeps compiled
    graphs pure). Outside: advance the global key chain (eager mode).
    """
    tr = current_trace_rng()
    if tr is not None:
        return tr.next_key()
    key = _chain()
    # concrete even under an EXTERNAL trace with no TraceRng installed
    # (shape inference eval_shape'ing a Dropout, a user jit over eager
    # ops): splitting inside the trace would store a TRACER into the
    # global chain and poison every later eager draw
    # (UnexpectedTracerError); compile-time eval keeps the chain eager
    # and hands the trace a constant subkey.
    with jax.ensure_compile_time_eval():
        new_key, sub = jax.random.split(key)
    _state.key = new_key
    return sub
