"""Static analysis of lowered StableHLO text: layout/precision op counts.

Extracted from tools/diagnose_step_hlo.py so the same counters serve both
the diagnosis CLI and chip-free regression tests: the pre-optimization
StableHLO of a jitted program is a deterministic function of the traced
graph, so counting `convert` / `transpose` / `convolution` / `dot_general`
ops (and the nominal element traffic through them) on CPU bounds what the
TPU backend will see — a perf guardrail that needs no chip.

    import jax, mxnet_tpu.hlo_stats as hs
    stats = hs.analyze_stablehlo(jax.jit(f).lower(*args).as_text())
    assert hs.convert_count_between(stats, "f32", "bf16") <= BUDGET
"""
from __future__ import annotations

import collections
import re

# "?" dims appear in dynamic-batch (jax.export symbolic-shape) modules;
# an unknown dim counts as 1 element in _elems, which keeps every count
# a LOWER bound — the direction the budgets ratchet against
_SHAPE_RE = re.compile(r"tensor<([0-9?x]*)x?([a-z0-9]+)>")
_OP_RE = re.compile(r"stablehlo\.(\w+)")


def _elems(shape_str):
    """Element count of a StableHLO shape prefix like '128x3x224x224'."""
    n = 1
    for d in shape_str.split("x"):
        if d.isdigit():
            n *= int(d)
    return n


def analyze_stablehlo(text):
    """Count the layout/precision ops in StableHLO text.

    Returns an OrderedDict of human-readable counters:

    * ``transpose_count`` / ``transpose_gelems`` — layout shuffles and the
      billions of elements they move;
    * ``convert_count`` / ``convert_pairs`` / ``convert_gelems`` — dtype
      converts broken down by ``src->dst`` pair with nominal element
      traffic per pair;
    * ``convolution`` / ``dot_general`` — MXU-op counts keyed by result
      element type;
    * ``total_ops`` / ``top_ops`` — overall op census.
    """
    out = collections.OrderedDict()
    op_counts = collections.Counter()
    transpose_elems = 0
    convert_pairs = collections.Counter()
    convert_elems = collections.Counter()
    conv_types = collections.Counter()
    dot_types = collections.Counter()

    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        op_counts[op] += 1
        if op == "transpose":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                transpose_elems += _elems(shapes[0][0])
        elif op == "convert":
            shapes = _SHAPE_RE.findall(line)
            if len(shapes) >= 2:
                pair = "%s->%s" % (shapes[0][1], shapes[-1][1])
                convert_pairs[pair] += 1
                convert_elems[pair] += _elems(shapes[0][0])
        elif op == "convolution":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                conv_types[shapes[-1][1]] += 1
        elif op == "dot_general":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                dot_types[shapes[-1][1]] += 1

    out["transpose_count"] = op_counts["transpose"]
    out["transpose_gelems"] = transpose_elems / 1e9
    out["convert_count"] = op_counts["convert"]
    out["convert_pairs"] = dict(convert_pairs.most_common())
    out["convert_gelems"] = {k: v / 1e9
                             for k, v in convert_elems.most_common()}
    out["convolution"] = dict(conv_types)
    out["dot_general"] = dict(dot_types)
    out["total_ops"] = sum(op_counts.values())
    out["top_ops"] = dict(op_counts.most_common(12))
    return out


_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

_ENTRY_RE = re.compile(r"func\.func\s+(?:public\s+)?@(\w+)\s*\(")
_DONOR_RE = re.compile(r"jax\.buffer_donor\s*=\s*true|tf\.aliasing_output")
_CUSTOM_CALL_RE = re.compile(r"stablehlo\.custom_call\s+@([\w.$-]+)")


def _matching_paren(text, open_idx):
    """Index just past the ')' matching the '(' at ``open_idx``; -1 if the
    text ends first (truncated module)."""
    depth = 0
    for i in range(open_idx, len(text)):
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i
    return -1


def entry_params(text):
    """Parse the entry computation's parameter list from StableHLO text.

    Returns a list of dicts — ``{"name", "dtype", "elems", "bytes",
    "donated"}`` in argument order — for the first ``func.func public``
    (falling back to any ``func.func``). A module with **zero entry
    computations** (e.g. an empty or constant-folded-away lowering)
    returns ``[]`` instead of raising, and parameters whose type is not a
    plain ranked tensor (token, tuple) are included with ``elems=0``.
    """
    m = None
    for cand in _ENTRY_RE.finditer(text):
        m = cand
        # prefer @main / the first public func; _ENTRY_RE already skips
        # private helper parens like stablehlo.reduce regions
        break
    if m is None:
        return []
    open_idx = text.index("(", m.end() - 1)
    close_idx = _matching_paren(text, open_idx)
    if close_idx < 0:
        return []
    sig = text[open_idx + 1:close_idx]
    params = []
    # split on top-level commas only (attr dicts contain commas)
    depth = 0
    start = 0
    parts = []
    for i, c in enumerate(sig):
        if c in "({<[":
            depth += 1
        elif c in ")}>]":
            depth -= 1
        elif c == "," and depth == 0:
            parts.append(sig[start:i])
            start = i + 1
    if sig[start:].strip():
        parts.append(sig[start:])
    for part in parts:
        part = part.strip()
        if not part:
            continue
        name = part.split(":", 1)[0].strip()
        tm = _SHAPE_RE.search(part)
        if tm:
            dtype = tm.group(2)
            elems = _elems(tm.group(1)) if tm.group(1) else 1
        else:
            dtype, elems = "unknown", 0
        params.append({
            "name": name,
            "dtype": dtype,
            "elems": elems,
            "bytes": elems * _DTYPE_BYTES.get(dtype, 4),
            "donated": bool(_DONOR_RE.search(part)),
        })
    return params


def custom_call_targets(text):
    """Counter of ``stablehlo.custom_call`` target names in the module.

    Robust to tuple-returning custom calls (``%0:2 = stablehlo.custom_call
    @target(...) : (...) -> (tensor<...>, tensor<...>)``) — the target is
    read from the op token itself, never from the result arity."""
    return collections.Counter(_CUSTOM_CALL_RE.findall(text))


# ops whose results are pure data movement / pointwise math: every byte
# they write is an intermediate XLA must either fuse away or spill to HBM.
# The *nominal* sum over them (pre-optimization) is an upper bound on the
# fusion work the backend has to do — and the number a fused Pallas
# epilogue (kernels/) removes from the program outright.
_ELEMENTWISE_OPS = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "select", "convert", "transpose", "negate", "exponential", "tanh",
    "logistic", "rsqrt", "sqrt", "compare", "clamp", "abs", "power",
    "and", "or", "xor", "broadcast_in_dim",
))


def elementwise_bytes(text):
    """(total_bytes, per_op_bytes) nominally written by elementwise and
    layout ops in the module.

    Counts the RESULT tensor of every op in ``_ELEMENTWISE_OPS`` (the last
    ``tensor<...>`` on the line — StableHLO prints the result type last).
    Pre-optimization this is a deterministic, chip-free proxy for the
    bytes-moved pressure the fusion pass (mxlint MXL505) budgets."""
    total = 0
    per_op = collections.Counter()
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m or m.group(1) not in _ELEMENTWISE_OPS:
            continue
        shapes = _SHAPE_RE.findall(line)
        if not shapes:
            continue
        shape_str, dtype = shapes[-1]
        b = _elems(shape_str) * _DTYPE_BYTES.get(dtype, 4)
        total += b
        per_op[m.group(1)] += b
    return total, per_op


_KERNEL_NAME_RE = re.compile(r'kernel_name\s*=\s*"([\w.$-]+)"')


def pallas_kernel_names(text):
    """Counter of Pallas ``kernel_name`` attributes in the module.

    A ``pl.pallas_call(..., name="mxk_foo")`` lowered for TPU shows up as
    a ``stablehlo.custom_call @tpu_custom_call`` whose backend config
    carries ``kernel_name = "mxk_foo"`` in plain text — so a chip-free
    ``jax.export``-for-TPU module proves which kernels the tier actually
    dispatched, no accelerator needed. Interpreter-mode lowerings inline
    to plain HLO and (correctly) report nothing here."""
    return collections.Counter(_KERNEL_NAME_RE.findall(text))


def convert_count_between(stats, a, b):
    """Total converts in either direction between element types ``a`` and
    ``b`` (e.g. ``("f32", "bf16")``) from an :func:`analyze_stablehlo`
    result."""
    pairs = stats.get("convert_pairs", {})
    return pairs.get("%s->%s" % (a, b), 0) + pairs.get("%s->%s" % (b, a), 0)


def convert_gelems_between(stats, a, b):
    """Nominal element traffic (Gelem) through converts between ``a`` and
    ``b`` in either direction."""
    g = stats.get("convert_gelems", {})
    return g.get("%s->%s" % (a, b), 0.0) + g.get("%s->%s" % (b, a), 0.0)
