"""Static analysis of lowered StableHLO text: layout/precision op counts.

Extracted from tools/diagnose_step_hlo.py so the same counters serve both
the diagnosis CLI and chip-free regression tests: the pre-optimization
StableHLO of a jitted program is a deterministic function of the traced
graph, so counting `convert` / `transpose` / `convolution` / `dot_general`
ops (and the nominal element traffic through them) on CPU bounds what the
TPU backend will see — a perf guardrail that needs no chip.

    import jax, mxnet_tpu.hlo_stats as hs
    stats = hs.analyze_stablehlo(jax.jit(f).lower(*args).as_text())
    assert hs.convert_count_between(stats, "f32", "bf16") <= BUDGET
"""
from __future__ import annotations

import collections
import re

_SHAPE_RE = re.compile(r"tensor<([0-9x]*)x?([a-z0-9]+)>")
_OP_RE = re.compile(r"stablehlo\.(\w+)")


def _elems(shape_str):
    """Element count of a StableHLO shape prefix like '128x3x224x224'."""
    n = 1
    for d in shape_str.split("x"):
        if d.isdigit():
            n *= int(d)
    return n


def analyze_stablehlo(text):
    """Count the layout/precision ops in StableHLO text.

    Returns an OrderedDict of human-readable counters:

    * ``transpose_count`` / ``transpose_gelems`` — layout shuffles and the
      billions of elements they move;
    * ``convert_count`` / ``convert_pairs`` / ``convert_gelems`` — dtype
      converts broken down by ``src->dst`` pair with nominal element
      traffic per pair;
    * ``convolution`` / ``dot_general`` — MXU-op counts keyed by result
      element type;
    * ``total_ops`` / ``top_ops`` — overall op census.
    """
    out = collections.OrderedDict()
    op_counts = collections.Counter()
    transpose_elems = 0
    convert_pairs = collections.Counter()
    convert_elems = collections.Counter()
    conv_types = collections.Counter()
    dot_types = collections.Counter()

    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        op_counts[op] += 1
        if op == "transpose":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                transpose_elems += _elems(shapes[0][0])
        elif op == "convert":
            shapes = _SHAPE_RE.findall(line)
            if len(shapes) >= 2:
                pair = "%s->%s" % (shapes[0][1], shapes[-1][1])
                convert_pairs[pair] += 1
                convert_elems[pair] += _elems(shapes[0][0])
        elif op == "convolution":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                conv_types[shapes[-1][1]] += 1
        elif op == "dot_general":
            shapes = _SHAPE_RE.findall(line)
            if shapes:
                dot_types[shapes[-1][1]] += 1

    out["transpose_count"] = op_counts["transpose"]
    out["transpose_gelems"] = transpose_elems / 1e9
    out["convert_count"] = op_counts["convert"]
    out["convert_pairs"] = dict(convert_pairs.most_common())
    out["convert_gelems"] = {k: v / 1e9
                             for k, v in convert_elems.most_common()}
    out["convolution"] = dict(conv_types)
    out["dot_general"] = dict(dot_types)
    out["total_ops"] = sum(op_counts.values())
    out["top_ops"] = dict(op_counts.most_common(12))
    return out


def convert_count_between(stats, a, b):
    """Total converts in either direction between element types ``a`` and
    ``b`` (e.g. ``("f32", "bf16")``) from an :func:`analyze_stablehlo`
    result."""
    pairs = stats.get("convert_pairs", {})
    return pairs.get("%s->%s" % (a, b), 0) + pairs.get("%s->%s" % (b, a), 0)


def convert_gelems_between(stats, a, b):
    """Nominal element traffic (Gelem) through converts between ``a`` and
    ``b`` in either direction."""
    g = stats.get("convert_gelems", {})
    return g.get("%s->%s" % (a, b), 0.0) + g.get("%s->%s" % (b, a), 0.0)
