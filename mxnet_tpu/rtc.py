"""Runtime kernel compilation (parity slot: python/mxnet/rtc.py).

The reference compiles CUDA C source at runtime (CudaModule/CudaKernel).
The TPU analog of runtime kernels is pallas (see ops/pallas_flash.py for
the pattern); arbitrary source-string compilation to TPU ISA is not a
supported workflow, so this module exists to fail loudly with guidance
rather than to emulate."""
from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]


class CudaModule:
    def __init__(self, *a, **kw):
        raise MXNetError(
            "rtc.CudaModule is CUDA-only. On TPU write a pallas kernel "
            "instead (jax.experimental.pallas; see "
            "mxnet_tpu/ops/pallas_flash.py for the pattern) or a CustomOp "
            "(mxnet_tpu/ops/custom_op.py) for host code.")


CudaKernel = CudaModule
