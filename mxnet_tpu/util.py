"""General utilities (parity: python/mxnet/util.py)."""
import os


def makedirs(d):
    """Create directories recursively if they don't exist."""
    os.makedirs(d, exist_ok=True)
