"""Subgraph partitioning framework (parity: src/operator/subgraph/
subgraph_property.h:54-155, partition_graph.cc, default_subgraph_property.cc).

The reference lets acceleration backends (MKLDNN, TensorRT) pattern-match
regions of the graph and replace them with single fused operators.  On
TPU, XLA already fuses aggressively, so the *performance* role is mostly
covered by the compiler — what this framework provides is the reference's
**extension point**: a registry of backends whose selectors claim chains
of nodes, which are then collapsed into one graph node executing the
sub-graph as a nested jax program (a natural place to drop in a pallas
kernel for a matched pattern).

Semantics mirrored from the reference:
* ``SubgraphSelector`` — stateful matcher: ``select`` starts a match,
  ``select_output`` extends it downstream, ``reset`` between attempts.
* ``SubgraphProperty`` — builds selectors and names the fused node.
* backends registered by name; ``Symbol.get_backend_symbol(name)``
  partitions, and the ``MXNET_SUBGRAPH_BACKEND`` env/config flag applies
  a backend inside ``simple_bind`` automatically.

Correctness contract kept simple and checkable: a match is a **linear
chain** whose interior outputs have no external consumers; auxiliary
states of interior ops (BatchNorm moving stats) are routed through the
fused node's aux slots, so training-time updates still land.
"""
from __future__ import annotations

from . import config as _config
from .ops.registry import Operator
from .symbol.symbol import Node, Symbol, Variable


class SubgraphSelector:
    """Decides which nodes join a subgraph (subgraph_property.h:54)."""

    def select(self, node):
        """Start a new match at ``node``?"""
        return False

    def select_output(self, node, output_node):
        """Extend the match from ``node`` to its consumer ``output_node``?"""
        return False

    def reset(self):
        """Called before each new match attempt."""


class SubgraphProperty:
    """A backend's partitioning rule (subgraph_property.h:93)."""

    #: name stamped on fused nodes
    op_name = "_sg_subgraph"

    def create_subgraph_selector(self):
        return SubgraphSelector()

    def subgraph_name(self, index):
        return "%s_%d" % (self.op_name, index)


_BACKENDS = {}


def register_backend(name, properties):
    """Register backend ``name`` with a list of SubgraphProperty."""
    _BACKENDS[name] = list(properties)


def get_backend(name):
    if name not in _BACKENDS:
        raise KeyError("unknown subgraph backend %r; registered: %s"
                       % (name, sorted(_BACKENDS)))
    return _BACKENDS[name]


# ---------------------------------------------------------------- partition
def _consumers(nodes):
    out = {}
    for n in nodes:
        for (p, _oi) in n.inputs:
            out.setdefault(id(p), []).append(n)
    return out


def _find_chains(sym, prop):
    """Greedy linear-chain matching in topo order (claimed nodes are
    skipped).  Returns list of chains (each a list of Nodes, head..tail)."""
    nodes = sym._topo()
    consumers = _consumers(nodes)
    head_ids = {id(n) for n, _ in sym._entries}
    claimed = set()
    chains = []
    for node in nodes:
        if node.is_variable or id(node) in claimed:
            continue
        selector = prop.create_subgraph_selector()
        selector.reset()
        if not selector.select(node):
            continue
        chain = [node]
        cur = node
        while True:
            # interior nodes must have exactly one consumer and must not be
            # graph outputs — otherwise their value escapes the subgraph
            outs = consumers.get(id(cur), [])
            if len(outs) != 1 or id(cur) in head_ids:
                break
            nxt = outs[0]
            if nxt.is_variable or id(nxt) in claimed:
                break
            if nxt.num_outputs() != 1:
                break
            if not selector.select_output(cur, nxt):
                break
            chain.append(nxt)
            cur = nxt
        if len(chain) > 1:
            claimed.update(id(n) for n in chain)
            chains.append(chain)
    return chains


def _take_key():
    """PRNG key for the nested eval: trace-scope key under jit, the eager
    chain otherwise — and a fixed key during abstract evaluation
    (jax.eval_shape runs ops outside any trace scope; splitting the eager
    global key there would leak a tracer into it)."""
    import jax
    from . import random as _random
    if _random.current_trace_rng() is not None:
        return _random.next_key()
    try:
        from jax._src.core import trace_state_clean
        abstract = not trace_state_clean()
    except ImportError:  # pragma: no cover - jax internals moved
        abstract = False
    if abstract:
        return jax.random.PRNGKey(0)
    return _random.next_key()


def _build_fused(chain, name):
    """Collapse ``chain`` into one Node executing the sub-graph."""
    from .executor import _graph_eval_fn

    member_ids = {id(n) for n in chain}
    tail = chain[-1]

    # external inputs in first-use order; aux vars split out
    ext_inputs = []        # list[(producer Node, out_idx)]
    ext_index = {}
    var_names = []
    for n in chain:
        aux_slots = set(getattr(n.op, "aux_inputs", ()) or ())
        for slot, (p, oi) in enumerate(n.inputs):
            if id(p) in member_ids:
                continue
            key = (id(p), oi)
            if key not in ext_index:
                ext_index[key] = len(ext_inputs)
                ext_inputs.append((p, oi, slot in aux_slots))
                var_names.append("in%d_%s" % (len(ext_inputs) - 1,
                                              p.name))

    # clone the chain over fresh Variables so the sub-symbol is closed
    placeholder = {}
    for i, (p, oi, _is_aux) in enumerate(ext_inputs):
        placeholder[(id(p), oi)] = Variable(var_names[i])._entries[0]
    clones = {}
    for n in chain:
        new_inputs = []
        for (p, oi) in n.inputs:
            if id(p) in member_ids:
                new_inputs.append((clones[id(p)], oi))
            else:
                new_inputs.append(placeholder[(id(p), oi)])
        clones[id(n)] = Node(n.op, n.name, new_inputs, dict(n.params),
                             dict(n.attrs))
    sub_sym = Symbol([(clones[id(tail)], 0)])
    sub_eval = _graph_eval_fn(sub_sym)

    aux_var_names = [var_names[i] for i, (_, _, a) in enumerate(ext_inputs)
                     if a]
    arg_slots = [i for i, (_, _, a) in enumerate(ext_inputs) if not a]
    aux_slots = [i for i, (_, _, a) in enumerate(ext_inputs) if a]

    def fused_fn(*ins, _training=False):
        arg_vals = {var_names[i]: ins[i] for i in arg_slots}
        aux_vals = {var_names[i]: ins[i] for i in aux_slots}
        outs, aux_updates = sub_eval(arg_vals, aux_vals, _take_key(),
                                     _training)
        if not aux_var_names:
            return outs[0]
        return tuple(outs) + tuple(aux_updates.get(v, aux_vals[v])
                                   for v in aux_var_names)

    def fused_shape_hook(in_shapes, params):
        # re-run inference over the sub-graph so interior hooks (e.g.
        # Convolution's weight-shape rule) complete the fused inputs
        from .symbol.symbol import _infer_shapes
        known = {var_names[i]: tuple(s)
                 for i, s in enumerate(in_shapes) if s is not None}
        res = _infer_shapes(sub_sym, known)
        return [res.get(("var", var_names[i]), in_shapes[i])
                for i in range(len(var_names))]

    def fused_dtype_hook(in_dtypes, params):
        from .symbol.symbol import _infer_types
        known = {var_names[i]: d
                 for i, d in enumerate(in_dtypes) if d is not None}
        res = _infer_types(sub_sym, known)
        in_d = [res.get(("var", var_names[i]), in_dtypes[i])
                for i in range(len(var_names))]
        out_d = [res.get((id(clones[id(tail)]), 0), in_d[0])]
        out_d += [in_d[i] for i in aux_slots]
        return in_d, out_d

    n_out = 1 + len(aux_var_names)
    op = Operator(name, fused_fn, num_outputs=n_out)
    op.aux_inputs = tuple(aux_slots)
    op.aux_outputs = tuple(range(1, n_out))
    op.num_visible_outputs = 1
    op.shape_hook = fused_shape_hook
    op.dtype_hook = fused_dtype_hook
    # keep the sub-symbol reachable for introspection/tests (Operator has
    # __slots__, functions have __dict__)
    fused_fn._subgraph_symbol = sub_sym

    fused = Node(op, name, [(p, oi) for (p, oi, _a) in ext_inputs], {},
                 {"__subgraph_op__": ",".join(n.op.name for n in chain)})
    return fused, tail


def partition(sym, backend_name):
    """Return a new Symbol with ``backend_name``'s properties applied
    (reference BuildSubgraph, partition_graph.cc)."""
    properties = get_backend(backend_name)
    out = sym
    for prop in properties:
        out = _apply_property(out, prop)
    return out


def _apply_property(sym, prop):
    chains = _find_chains(sym, prop)
    if not chains:
        return sym
    # (tail node id) -> fused Node
    replacement = {}
    for i, chain in enumerate(chains):
        fused, tail = _build_fused(chain, prop.subgraph_name(i))
        replacement[id(tail)] = fused

    # rebuild the graph with tails swapped for fused nodes — iterative
    # postorder (like Symbol._topo) so deep graphs don't hit the Python
    # recursion limit
    memo = {}
    roots = [n for (n, _oi) in sym._entries]
    stack = [(n, False) for n in reversed(roots)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in memo:
            continue
        src = replacement.get(id(node), node)
        if not expanded:
            stack.append((node, True))
            for (p, _oi) in reversed(src.inputs):
                if id(p) not in memo:
                    stack.append((p, False))
            continue
        if node.is_variable and id(node) not in replacement:
            memo[id(node)] = node
        else:
            memo[id(node)] = Node(
                src.op, src.name,
                [(memo[id(p)], oi) for (p, oi) in src.inputs],
                dict(src.params), dict(src.attrs))

    entries = [(memo[id(n)], oi) for (n, oi) in sym._entries]
    return Symbol(entries)


# ------------------------------------------------------------- default bk
class _ConvBNActSelector(SubgraphSelector):
    """conv -> bn -> relu (any prefix length >= 2) — the classic fusion
    the reference's MKLDNN property targets (default_subgraph_property)."""

    def select(self, node):
        return node.op.name == "Convolution"

    def select_output(self, node, output_node):
        if node.op.name == "Convolution":
            return output_node.op.name == "BatchNorm"
        if node.op.name == "BatchNorm":
            return (output_node.op.name == "Activation"
                    and output_node.params.get("act_type") == "relu")
        return False


class ConvBNActProperty(SubgraphProperty):
    op_name = "_sg_conv_bn_act"

    def create_subgraph_selector(self):
        return _ConvBNActSelector()


register_backend("default", [ConvBNActProperty()])


def maybe_partition_for_bind(sym):
    """simple_bind hook: apply MXNET_SUBGRAPH_BACKEND if set."""
    backend = _config.flags.subgraph_backend
    if backend:
        return partition(sym, backend)
    return sym
