"""Sharded RecordIO streams + the streaming DataIter (docs/data.md).

:class:`ShardedRecordStream` partitions a RecordIO file set across dp
ranks so the fleet covers **every record exactly once per epoch**:

* per-epoch seeded shuffle — file order and within-file order both come
  from ``RandomState(seed + epoch)``, consumed identically on every rank
  (the plan is a pure function of ``(paths, seed, epoch)``, so all ranks
  agree on it without communicating);
* file-level + within-file strided sharding — for the file at position
  ``j`` of the epoch's file permutation, rank ``r`` reads the shuffled
  keys ``[(r + j) % world :: world]``. The per-file stride offsets are a
  permutation of ``0..world-1``, so the strided slices partition each
  file; rotating the offset with ``j`` keeps short files from starving
  high ranks.

The stream position is a resumable ``(epoch, shard, offset)`` cursor
(``shard`` = index into this rank's per-epoch file sequence, ``offset``
= records consumed within it). :class:`StreamingDataIter` attaches the
cursor to every delivered batch, so ``Module.fit`` can snapshot the
CONSUMED position into a checkpoint and ``seek`` back to it bitwise —
O(1) instead of the O(steps) batch-skip replay (docs/fault_tolerance.md).

Decode/augment runs in parallel on the ``image_record_iter`` worker
layout: each batch splits into P part jobs with per-part RNGs seeded
``(seed + epoch*1000003 + batch*1009 + part)`` — the same idiom as
``ImageRecordIter``, and the reason augmentation replays bitwise after a
cursor seek (epoch and batch index are both cursor-derived).
"""
from __future__ import annotations

import threading

import numpy as _np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..io.image_record_iter import _build_augmenter, _RecordSource
from .pipeline import PrefetchQueue

__all__ = ["ShardedRecordStream", "StreamingDataIter", "RawTensorDecoder",
           "ImageDecoder"]


class ShardedRecordStream:
    """Exactly-once strided reader over a sharded RecordIO file set.

    ``paths`` is one ``.rec`` path or a list (each with its ``.idx``
    sidecar unless the native scanner is available). ``rank``/``world``
    select this reader's stride of the fleet-wide record set.
    """

    def __init__(self, paths, rank=0, world=1, shuffle=True, seed=0,
                 epoch=0):
        if isinstance(paths, str):
            paths = [paths]
        if not paths:
            raise ValueError("ShardedRecordStream needs at least one file")
        if world <= 0 or not 0 <= rank < world:
            raise ValueError("bad rank/world: %r/%r" % (rank, world))
        self._paths = list(paths)
        self._rank = int(rank)
        self._world = int(world)
        self._shuffle = bool(shuffle)
        self._seed = int(seed)
        self._sources = [_RecordSource(p) for p in self._paths]
        self._counts = [len(s) for s in self._sources]
        if sum(self._counts) == 0:
            raise MXNetError("empty RecordIO set: %r" % (self._paths,))
        self._epoch = int(epoch)
        self._shard = 0
        self._offset = 0
        self._plan = None

    # ---------------------------------------------------------------- plan
    @property
    def epoch(self):
        return self._epoch

    @property
    def seed(self):
        return self._seed

    def _epoch_plan(self):
        if self._plan is not None:
            return self._plan
        rs = _np.random.RandomState(self._seed + self._epoch)
        nfiles = len(self._sources)
        if self._shuffle:
            file_perm = rs.permutation(nfiles)
        else:
            file_perm = _np.arange(nfiles)
        plan = []
        for j, fi in enumerate(file_perm):
            fi = int(fi)
            keys = (rs.permutation(self._counts[fi]) if self._shuffle
                    else _np.arange(self._counts[fi]))
            off = (self._rank + j) % self._world
            plan.append((fi, keys[off::self._world]))
        self._plan = plan
        return plan

    def records_per_epoch(self):
        """This rank's record count for the CURRENT epoch (the strided
        split can differ by ±1 per file across epochs as the stride
        offsets rotate with the file permutation)."""
        return sum(len(keys) for _, keys in self._epoch_plan())

    def records_consumed(self):
        """Records this rank has consumed within the current epoch."""
        plan = self._epoch_plan()
        done = sum(len(keys) for _, keys in plan[:self._shard])
        return done + self._offset

    # ------------------------------------------------------------- reading
    def read_next(self):
        """Next raw record's bytes, or None at epoch end. Advances the
        cursor; single-threaded by contract (one feeder per stream)."""
        plan = self._epoch_plan()
        while self._shard < len(plan):
            fi, keys = plan[self._shard]
            if self._offset < len(keys):
                rec = self._sources[fi].read(int(keys[self._offset]))
                self._offset += 1
                return rec
            self._shard += 1
            self._offset = 0
        return None

    def __iter__(self):
        while True:
            rec = self.read_next()
            if rec is None:
                return
            yield rec

    def next_epoch(self):
        self._epoch += 1
        self._shard = 0
        self._offset = 0
        self._plan = None

    # -------------------------------------------------------------- cursor
    def cursor(self):
        """JSON-able resumable position. Carries the sharding fingerprint
        so a seek under a different fleet shape fails loudly instead of
        silently replaying someone else's stride."""
        return {"epoch": self._epoch, "shard": self._shard,
                "offset": self._offset, "rank": self._rank,
                "world": self._world, "seed": self._seed}

    def seek(self, cursor):
        for key in ("rank", "world", "seed"):
            if key in cursor and int(cursor[key]) != getattr(
                    self, "_" + key):
                raise MXNetError(
                    "cursor %s=%r does not match this stream's %s=%r — "
                    "resharding a cursor needs a fresh epoch, not a seek"
                    % (key, cursor[key], key, getattr(self, "_" + key)))
        self._epoch = int(cursor["epoch"])
        self._shard = int(cursor["shard"])
        self._offset = int(cursor["offset"])
        self._plan = None


class RawTensorDecoder:
    """Decode records whose payload is ONE sample's raw bytes in
    ``data_shape`` order (as packed by tools/make_recordio.py); the label
    comes from the IRHeader. No randomness — a stream of these feeds
    ``Module.fit`` bitwise-identically to an in-memory ``NDArrayIter``
    over the same rows (pinned by tests/test_step_sync_budget.py)."""

    randomized = False

    def __init__(self, data_shape, label_width=1, dtype=_np.float32):
        self.data_shape = tuple(data_shape)
        self.label_width = int(label_width)
        self.data_dtype = _np.dtype(dtype)

    def __call__(self, rec, out_data, out_label, j, rng):
        from .. import recordio as _rio
        header, payload = _rio.unpack(rec)
        out_data[j] = _np.frombuffer(
            payload, self.data_dtype).reshape(self.data_shape)
        lab = _np.asarray(header.label).reshape(-1)
        out_label[j] = lab[0] if self.label_width == 1 \
            else lab[:self.label_width]


class ImageDecoder:
    """JPEG decode + the reference default augmenter (HWC BGR uint8 ->
    CHW float32 RGB) — the same ``_build_augmenter`` transform
    ``ImageRecordIter`` runs, so both tiers share one augmentation
    definition. ``aug_params`` as in ImageRecordIter (resize, rand_crop,
    rand_mirror, mean/std, scale, pad, ...)."""

    randomized = True

    def __init__(self, data_shape, label_width=1, **aug_params):
        self.data_shape = tuple(data_shape)
        self.label_width = int(label_width)
        self.data_dtype = _np.dtype(_np.float32)
        self._aug = _build_augmenter(self.data_shape, **aug_params)

    def __call__(self, rec, out_data, out_label, j, rng):
        import cv2
        from .. import recordio as _rio
        header, img_bytes = _rio.unpack(rec)
        img = cv2.imdecode(
            _np.frombuffer(img_bytes, _np.uint8), cv2.IMREAD_COLOR)
        if img is None:
            raise MXNetError("corrupt/undecodable image record")
        out_data[j] = self._aug(img, rng)
        lab = _np.asarray(header.label).reshape(-1)
        out_label[j] = lab[0] if self.label_width == 1 \
            else lab[:self.label_width]


class StreamingDataIter(DataIter):
    """DataIter over a :class:`ShardedRecordStream` with parallel
    decode/augment and a resumable cursor.

    A feeder thread pulls records, splits each batch into part jobs on a
    thread pool (cv2 releases the GIL, so parts decode concurrently),
    and pushes finished ``DataBatch``es through a :class:`PrefetchQueue`
    (the bounded put is the pipeline's backpressure). Every queued batch
    carries the stream cursor taken right after its records were pulled,
    so ``get_cursor()`` always reflects the position of the batch the
    CONSUMER last saw — never the feeder's read-ahead. ``reset()``
    rewinds the stream to that consumed position before restarting, so
    prefetched-but-undelivered batches are re-read, not lost.

    The short epoch tail (fewer than ``batch_size`` records) is dropped —
    every delivered batch is full, and the cursor stays on the exact
    record grid a resumed run re-derives.
    """

    def __init__(self, stream, decoder, batch_size, decode_threads=None,
                 prefetch_depth=None, ctx=None, data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        from ..config import flags as _flags
        self._stream = stream
        self._decoder = decoder
        self._ctx = ctx
        self.data_name = data_name
        self.label_name = label_name
        self._nthreads = max(1, int(decode_threads
                                    or _flags.data_decode_threads
                                    or _flags.cpu_worker_nthreads))
        self._depth = max(2, int(prefetch_depth or _flags.data_feed_depth))
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(self._nthreads)
        self._pq = None
        self._feeder = None
        self._done = False
        self._last_cursor = stream.cursor()
        self.seeks = 0        # test instrumentation: cursor-resume count
        self._start()

    # ------------------------------------------------------------ metadata
    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self._decoder.data_shape,
                         self._decoder.data_dtype)]

    @property
    def provide_label(self):
        w = self._decoder.label_width
        shape = (self.batch_size,) if w == 1 else (self.batch_size, w)
        return [DataDesc(self.label_name, shape)]

    @property
    def num_batches(self):
        return self._stream.records_per_epoch() // self.batch_size

    def queue_depth(self):
        """Host-held prefetch depth (for ``data/queue_depth`` telemetry)."""
        pq = self._pq
        return pq.qsize() if pq is not None else 0

    # -------------------------------------------------------------- feeder
    def _start(self):
        pq = self._pq = PrefetchQueue(self._depth)
        self._feeder = threading.Thread(
            target=self._feed_epoch, args=(pq,), daemon=True)
        self._feeder.start()

    def _feed_epoch(self, pq):
        try:
            self._feed_epoch_inner(pq)
        except BaseException as e:
            pq.put(e)
        pq.put_sentinel()

    def _decode_part(self, recs, out_data, out_label, offset, rng):
        for j, rec in enumerate(recs):
            self._decoder(rec, out_data, out_label, offset + j, rng)

    def _feed_epoch_inner(self, pq):
        from ..ndarray import ndarray as _nd
        B = self.batch_size
        P = self._nthreads
        epoch = self._stream.epoch
        seed = self._stream.seed
        w = self._decoder.label_width
        lshape = (w,) if w > 1 else ()
        b = self._stream.records_consumed() // B
        while not pq.stopped:
            recs = []
            while len(recs) < B:
                rec = self._stream.read_next()
                if rec is None:
                    return  # epoch end (short tail dropped)
                recs.append(rec)
            # the cursor rides the batch: taken after ITS records, before
            # the feeder reads ahead
            cursor = self._stream.cursor()
            data = _np.empty((B,) + self._decoder.data_shape,
                             self._decoder.data_dtype)
            label = _np.empty((B,) + lshape, _np.float32)
            bounds = [(p * B // P, (p + 1) * B // P) for p in range(P)]
            rngs = [_np.random.RandomState(
                (seed + epoch * 1000003 + b * 1009 + p))
                for p in range(P)]
            futs = [self._pool.submit(self._decode_part, recs[lo:hi],
                                      data, label, lo, rngs[p])
                    for p, (lo, hi) in enumerate(bounds) if lo != hi]
            for f in futs:
                f.result()   # re-raise decode errors on the feeder
            batch = DataBatch(data=[_nd.array(data, ctx=self._ctx)],
                              label=[_nd.array(label, ctx=self._ctx)],
                              pad=0)
            if not pq.put((batch, cursor)):
                return
            b += 1

    # ------------------------------------------------------------ iterator
    def next(self):
        if self._done:
            raise StopIteration
        try:
            batch, cursor = self._pq.get()
        except StopIteration:
            self._done = True
            # clean epoch end: advance to the next epoch's plan so the
            # post-epoch reset() starts fresh (ImageRecordIter semantics)
            self._stream.next_epoch()
            self._last_cursor = self._stream.cursor()
            raise
        self._last_cursor = cursor
        return batch

    def get_cursor(self):
        """Resumable position of the last CONSUMED batch (a fresh copy —
        safe to stash in a checkpoint while iteration continues)."""
        return dict(self._last_cursor)

    def seek(self, cursor):
        """Reposition to a checkpointed cursor: the next delivered batch
        is the one that followed it, bitwise (decode RNGs are re-derived
        from the cursor's epoch/batch index)."""
        self._shutdown_feeder()
        self._stream.seek(cursor)
        self._last_cursor = dict(cursor)
        self._done = False
        self.seeks += 1
        self._start()

    def reset(self):
        self._shutdown_feeder()
        # rewind to the consumed position: the feeder read ahead of the
        # consumer, and those records belong to the NEXT generation
        self._stream.seek(self._last_cursor)
        self._done = False
        self._start()

    def _shutdown_feeder(self):
        if self._pq is not None:
            self._pq.shutdown(self._feeder, timeout=30.0)

    def close(self):
        self._shutdown_feeder()
        self._pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
