"""The bounded-queue backpressure/shutdown primitive shared by every
prefetching producer.

``PrefetchingIter`` (io/io.py), ``ImageRecordIter``
(io/image_record_iter.py), and the streaming tier's feeders all have the
same shape: a producer thread pushes finished items into a bounded queue
(the pipeline's backpressure), the consumer pops, and a reset/close must
never deadlock against a producer blocked on a full queue. Before this
module each iterator carried its own copy of that machinery; the copies
had drifted (different drain loops, different sentinel delivery). One
implementation, one contract:

* ``put`` is bounded and keeps observing the stop flag — a plain
  ``Queue.put`` can block forever on a full queue the consumer abandoned.
* The ``None`` sentinel must ALWAYS arrive (unless stopped) — a dead
  producer surfaces as ``StopIteration``/an error in the consumer, never
  as a hang on ``get()``.
* An ``Exception`` pushed through the queue propagates to the consumer's
  ``get`` (async errors cross the thread boundary).
* ``shutdown`` signals stop FIRST, then drains while joining, so a
  producer blocked mid-``put`` can finish and observe the flag — the
  mid-epoch-close race pinned by tests/test_data_stream.py.

A queue instance belongs to ONE producer generation: reset creates a
fresh ``PrefetchQueue`` after shutting the old one down, so a zombie
producer can never feed stale items into the new generation's queue.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

__all__ = ["PrefetchQueue"]

_PUT_POLL_S = 0.1


class PrefetchQueue:
    """Bounded producer/consumer queue with the shared shutdown protocol."""

    def __init__(self, depth):
        self._q = _queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()

    # ------------------------------------------------------------- producer
    def put(self, item):
        """Bounded put that keeps observing the stop flag. Returns False
        (item dropped) when the queue was stopped before the put landed —
        the producer should exit."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=_PUT_POLL_S)
                return True
            except _queue.Full:
                continue  # consumer will pop, or shutdown() will stop us
        return False

    def put_sentinel(self):
        """Deliver the end-of-stream ``None`` sentinel (same bounded put —
        a stopped queue has no consumer left to wake)."""
        return self.put(None)

    # ------------------------------------------------------------- consumer
    def get(self, block=True, timeout=None):
        """Pop one item. Raises ``StopIteration`` on the sentinel and
        re-raises an exception the producer pushed."""
        item = self._q.get(block=block, timeout=timeout)
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def get_raw(self):
        """Blocking pop with NO sentinel/exception interpretation, for
        consumers that need the reference iterator's own error surface
        (ImageRecordIter wraps pipeline errors in MXNetError)."""
        return self._q.get()

    def qsize(self):
        return self._q.qsize()

    # ------------------------------------------------------------- shutdown
    @property
    def stopped(self):
        return self._stop.is_set()

    def stop(self):
        self._stop.set()

    def wait_stop(self, timeout):
        """Producer-side backpressure sleep that wakes early on stop."""
        return self._stop.wait(timeout)

    def drain(self):
        """Empty the queue without blocking (unblocks a producer stuck in
        ``put``; its NEXT put observes the stop flag and returns False)."""
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass

    def shutdown(self, thread, timeout=5.0):
        """Signal stop, then drain-while-joining ``thread`` until it dies
        or ``timeout`` elapses. Order matters: signal FIRST, so a producer
        blocked on a full queue can finish its put and observe the flag.
        Returns True when the thread is dead (or was never started)."""
        self._stop.set()
        if thread is None or not thread.is_alive():
            return True
        # monotonic: an NTP step during shutdown must not turn the join
        # budget into zero (or into hours)
        deadline = time.monotonic() + timeout
        while thread.is_alive() and time.monotonic() < deadline:
            self.drain()
            thread.join(timeout=0.05)
        return not thread.is_alive()
