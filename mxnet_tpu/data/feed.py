"""StagedKFeed: the zero-stall K-step device feed (docs/data.md).

``FusedStep.run_k`` scans a jitted step over stacked ``(K, batch, ...)``
feeds. Without staging, the host builds that stacked buffer (cast +
``jnp.stack`` + ``device_put``) inside the dispatch call — serial with
the step loop, so every window pays the H2D latency before its dispatch
can issue. :class:`StagedKFeed` moves that work onto a feeder thread and
double-buffers it: while window ``W`` is in flight on the device, the
feeder is already pulling window ``W+1``'s K batches from the iterator
and committing them to the device layout (PJRT H2D is async, so the
copy itself overlaps compute). ``Module.fit`` then consumes
device-resident windows with zero added host syncs — the one-d2h-per-
window budget is pinned by tests/test_step_sync_budget.py.

What is deliberately NOT staged: PRNG keys and optimizer hyper-params.
Both advance deterministic host-side chains that checkpoint snapshots
capture at window boundaries; pre-drawing them for future windows would
put the saved chain ahead of the training position and break bitwise
kill/resume. The feeder stages data only — a pure function of the
batches — so the staged path is bitwise-identical to the unstaged one.

Cursor discipline: when the iterator exposes ``get_cursor``, the feeder
snapshots it right after pulling each window's batches (the feeder is
the only consumer, so that IS the consumed position when the window
commits) and attaches it to the window for the checkpoint path.
"""
from __future__ import annotations

import threading

from .pipeline import PrefetchQueue

__all__ = ["StagedKFeed", "StagedWindow"]


class StagedWindow:
    """One K-step window: the host batches (labels/metadata for metrics
    and callbacks), the pre-staged device feed (None on short tails —
    those take the per-step path), the iterator cursor after these
    batches, and the window's host-known H2D byte count."""

    __slots__ = ("batches", "staged", "cursor", "h2d_bytes")

    def __init__(self, batches, staged=None, cursor=None, h2d_bytes=0):
        self.batches = batches
        self.staged = staged
        self.cursor = cursor
        self.h2d_bytes = h2d_bytes


class StagedKFeed:
    """Double-buffered window stager between a DataIter and fit's
    grouped loop.

    ``stage_fn(batches)`` is the module's host→device staging hook
    (``Module._stage_group``): it returns the opaque staged-feed payload
    ``run_k`` accepts plus the window's H2D byte count. ``depth`` bounds
    the staged windows in flight (2 = classic double buffering; staged
    windows hold device memory, so keep it small).
    """

    def __init__(self, data_iter, k, stage_fn, depth=2, cursor_fn=None):
        self._it = data_iter
        self._k = max(2, int(k))
        self._stage_fn = stage_fn
        self._cursor_fn = cursor_fn
        self._pq = PrefetchQueue(max(1, int(depth)))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pq = self._pq
        try:
            while not pq.stopped:
                batches = []
                ended = False
                while len(batches) < self._k:
                    try:
                        batches.append(next(self._it))
                    except StopIteration:
                        ended = True
                        break
                if not batches:
                    break
                cursor = self._cursor_fn() if self._cursor_fn else None
                staged, nbytes = None, 0
                if len(batches) == self._k:
                    # full window: commit to the stacked device layout
                    # now, overlapping the in-flight dispatch. Tails ride
                    # unstaged — fit's per-step path handles them.
                    staged, nbytes = self._stage_fn(batches)
                if not pq.put(StagedWindow(batches, staged, cursor,
                                           nbytes)):
                    return
                if ended:
                    break
        except BaseException as e:
            pq.put(e)
        pq.put_sentinel()

    def next_window(self):
        """Next :class:`StagedWindow`; raises StopIteration at epoch end
        and re-raises feeder errors. Blocking time here is the fit
        loop's input stall (``data/input_stall_ms``)."""
        return self._pq.get()

    def queue_depth(self):
        return self._pq.qsize()

    def close(self):
        self._pq.shutdown(self._thread, timeout=30.0)
