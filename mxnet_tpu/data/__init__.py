"""Streaming ingestion tier: sharded record streams feeding the K-step
dispatch (docs/data.md).

The pieces, bottom-up:

* ``pipeline`` — the ONE bounded-queue backpressure/shutdown primitive
  every prefetching producer in the repo shares
  (:class:`PrefetchQueue`; also used by ``io.PrefetchingIter`` and
  ``io.ImageRecordIter``).
* ``record_stream`` — :class:`ShardedRecordStream` partitions a RecordIO
  file set across dp ranks (every record exactly once per epoch per
  fleet) with a resumable ``(epoch, shard, offset)`` cursor, and
  :class:`StreamingDataIter` turns it into a ``DataIter`` with parallel
  decode/augment and a bitwise kill/resume cursor that rides
  ``CheckpointManager``.
* ``feed`` — :class:`StagedKFeed`, the zero-stall K-step device feed:
  double-buffers the next window's K batches into the stacked
  device-resident layout ``FusedStep.run_k`` scans over, with the async
  H2D overlapped against the in-flight dispatch.
"""
from __future__ import annotations

from mxnet_tpu.data.pipeline import PrefetchQueue
from mxnet_tpu.data.record_stream import (
    ImageDecoder, RawTensorDecoder, ShardedRecordStream, StreamingDataIter,
)
from mxnet_tpu.data.feed import StagedKFeed, StagedWindow

__all__ = [
    "PrefetchQueue", "ShardedRecordStream", "StreamingDataIter",
    "RawTensorDecoder", "ImageDecoder", "StagedKFeed", "StagedWindow",
]
