"""Server-role entry point (parity slot: python/mxnet/kvstore_server.py).

The reference's dist kvstore runs dedicated parameter-server processes;
this framework has NO servers — aggregation is a symmetric all-reduce
over the jax.distributed process group (docs/distributed.md). Reference
launch scripts that spawn server/scheduler roles keep working: those
processes call ``_init_kvstore_server_module()``, which here logs the
design note and exits the blocking role loop immediately instead of
serving forever."""
import logging
import os

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """No-op stand-in for the ps-lite server loop."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):
        # warning level: the root logger shows it unconfigured, so the
        # operator sees WHY the server process exited
        logging.warning(
            "kvstore_server: this runtime has no parameter servers — "
            "gradient aggregation is an all-reduce over the worker group "
            "(see docs/distributed.md); server process exiting cleanly")


def _init_kvstore_server_module():
    """Reference contract (kvstore_server.py:85): invoked at package
    import on server/scheduler-role processes, runs the (here: no-op)
    server loop, then EXITS so the host never falls through into the
    user training script as a stray out-of-group worker."""
    import sys
    role = os.environ.get("DMLC_ROLE", "")
    if role in ("server", "scheduler"):
        KVStoreServer().run()
        sys.exit(0)
