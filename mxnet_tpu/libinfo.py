"""Library location info (parity: python/mxnet/libinfo.py)."""
import os

__version__ = "0.1.0"


def find_lib_path():
    """Paths to the native runtime library (libmxtpu.so)."""
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    candidates = [os.path.join(curr, "libmxtpu.so"),
                  os.path.join(curr, "../src/libmxtpu.so")]
    paths = [p for p in candidates if os.path.exists(p)]
    if not paths:
        raise RuntimeError("Cannot find libmxtpu.so: run `make -C src` "
                           "(pure-python fallbacks remain available)")
    return paths


def find_include_path():
    """Path to the C++ runtime sources/headers."""
    curr = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    src = os.path.join(curr, "..", "src")
    if os.path.isdir(src):
        return os.path.normpath(src)
    raise RuntimeError("Cannot find src/ include path")
