"""Evaluation metrics (parity: python/mxnet/metric.py, 1,424 LoC — registry of
~15 metrics + CompositeEvalMetric + CustomMetric)."""
from __future__ import annotations

import math

import numpy as _np

from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
               "negativeloglikelihood", "top_k_accuracy": "topkaccuracy",
               "pearsonr": "pearsoncorrelation"}
    name = aliases.get(name, name)
    return _METRIC_REGISTRY[name](*args, **kwargs)


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class _HostArray(_np.ndarray):
    """numpy view that still answers the NDArray host API, so user
    metrics written against the reference (``preds[0].asnumpy()``) keep
    working after the batched one-sync fetch below."""

    def asnumpy(self):
        return _np.asarray(self)


def _fetch_lists(*array_lists):
    """Move several lists of label/pred arrays to host in ONE
    ``jax.device_get`` of the whole pytree (one blocking device->host
    sync) instead of one ``asnumpy()`` round-trip per array. Host-side
    values pass through untouched. Returns the lists as numpy arrays
    (``asnumpy()``-compatible views)."""
    devs = [[x._data if isinstance(x, NDArray) else x for x in lst]
            for lst in array_lists]
    pending = [d for lst in devs for d in lst
               if hasattr(d, "block_until_ready")]
    if pending:
        from . import profiler as _profiler
        _profiler.record_host_sync(
            "d2h", sum(int(getattr(d, "nbytes", 0)) for d in pending))
        import jax
        devs = jax.device_get(devs)
    return [[_np.asarray(x).view(_HostArray) for x in lst] for lst in devs]


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names if n in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names if n in label]
        else:
            label = list(label.values())
        # multi-output modules: one batched fetch, not one sync per array
        label, pred = _fetch_lists(label, pred)
        self.update(label, pred)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        # fetch once for ALL sub-metrics, not once per sub-metric per array
        labels, preds = _fetch_lists(labels, preds)
        for m in self.metrics:
            m.update(labels, preds)

    def update_dict(self, labels, preds):
        lk, pk = list(labels), list(preds)
        lv, pv = _fetch_lists([labels[k] for k in lk], [preds[k] for k in pk])
        labels, preds = dict(zip(lk, lv)), dict(zip(pk, pv))
        for m in self.metrics:
            m.update_dict(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


def _check(labels, preds):
    if len(labels) != len(preds):
        raise ValueError("labels/preds count mismatch: %d vs %d"
                         % (len(labels), len(preds)))


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32).ravel()
            label = label.astype(_np.int32).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype(_np.int32)
            pred = _as_np(pred)
            idx = _np.argsort(pred, axis=1)[:, -self.top_k:]
            self.sum_metric += (idx == label.reshape(-1, 1)).any(axis=1).sum()
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(_np.int32)
            pred = _as_np(pred)
            pred = (pred[:, 1] > 0.5).astype(_np.int32) if pred.ndim == 2 \
                else (pred > 0.5).astype(_np.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(_np.int32)
            pred = _as_np(pred)
            pred = (pred[:, 1] > 0.5).astype(_np.int32) if pred.ndim == 2 \
                else (pred > 0.5).astype(_np.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            den = math.sqrt(max((self._tp + self._fp) * (self._tp + self._fn)
                                * (self._tn + self._fp) * (self._tn + self._fn),
                                1e-12))
            self.sum_metric = (self._tp * self._tn - self._fp * self._fn) / den
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        _check(labels, preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(_np.int64)
            pred = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.log(_np.maximum(probs, 1e-10)).sum()
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += _np.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label.astype(_np.int64)]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred).ravel()
            self.sum_metric += _np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of the raw loss output (reference Loss metric)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            loss = _as_np(pred).sum()
            self.sum_metric += loss
            self.num_inst += _as_np(pred).size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__ if feval.__name__ != "<lambda>" else "custom"
        super().__init__("custom(%s)" % name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            _check(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (reference metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
