"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

Reference role: none — the reference predates MoE serving; this fills
the ``ep`` slot of the framework's parallelism matrix (dp/tp/pp/sp/ep).

TPU-native design (GShard recipe, Lepikhin et al. 2020): top-1 routing
with a fixed per-expert capacity produces STATIC-shape dispatch/combine
tensors, so the whole layer is three einsums XLA can schedule; the
expert weights carry a leading expert axis annotated ``P("ep", ...)``
and GSPMD inserts the all_to_all where the token dimension meets the
expert dimension. Dropped tokens (over capacity) pass through on the
residual path, exactly as in GShard.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["moe_layer", "init_moe_params", "shard_moe_params",
           "aux_load_balance_loss"]


def init_moe_params(rng, d_model, d_hidden, n_expert, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(rng), 3)
    s1 = 1.0 / math.sqrt(d_model)
    return {
        "gate": jax.random.normal(k1, (d_model, n_expert), dtype) * s1,
        "w1": jax.random.normal(k2, (n_expert, d_model, d_hidden),
                                dtype) * s1,
        "w2": jax.random.normal(k3, (n_expert, d_hidden, d_model),
                                dtype) / math.sqrt(d_hidden),
    }


def shard_moe_params(params, mesh, axis_name="ep"):
    """Experts split across ``axis_name``; the gate is replicated."""
    return {
        "gate": jax.device_put(params["gate"],
                               NamedSharding(mesh, P())),
        "w1": jax.device_put(params["w1"],
                             NamedSharding(mesh, P(axis_name, None, None))),
        "w2": jax.device_put(params["w2"],
                             NamedSharding(mesh, P(axis_name, None, None))),
    }


def moe_layer(params, x, capacity_factor=2.0):
    """Top-1 MoE FFN: x (N, d) -> (N, d).

    Static shapes throughout: dispatch (N, E, C) one-hots route tokens to
    their expert's capacity slots; tokens past capacity are dropped (pass
    through via the residual). With ``params`` sharded by
    :func:`shard_moe_params`, the dispatch einsum's output is sharded
    P(ep, ...) and XLA materializes the token exchange as an all_to_all
    over the ``ep`` axis — no hand-written collective.
    """
    n, d = x.shape
    e = params["gate"].shape[1]
    c = max(1, int(math.ceil(n / e * capacity_factor)))

    logits = x @ params["gate"]                       # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # (N,)
    gate_val = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]

    onehot = jax.nn.one_hot(expert, e, dtype=x.dtype)         # (N, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot         # slot idx
    keep = (pos < c).astype(x.dtype) * onehot
    slot = jax.nn.one_hot(pos.astype(jnp.int32), c, dtype=x.dtype)
    dispatch = keep[:, :, None] * slot                        # (N, E, C)

    xin = jnp.einsum("nec,nd->ecd", dispatch, x)              # (E, C, d)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin, params["w1"]))
    out_e = jnp.einsum("ech,ehd->ecd", h, params["w2"])       # (E, C, d)
    combine = dispatch * gate_val[:, None, None]              # (N, E, C)
    y = jnp.einsum("nec,ecd->nd", combine, out_e)
    # dropped tokens (and all non-expert mass) ride the residual
    return x + y


def aux_load_balance_loss(params, x):
    """GShard auxiliary loss: mean(expert_fraction * router_prob) * E^2 —
    add (scaled) to the training loss to keep routing balanced."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    e = probs.shape[-1]
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), e,
                                   dtype=x.dtype), axis=0)
    return jnp.mean(frac * jnp.mean(probs, axis=0)) * (e * e)
