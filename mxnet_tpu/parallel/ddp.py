"""Bucketed, backward-overlapped gradient all-reduce for data parallelism.

The kvstore ``dist_sync`` path (kvstore.py -> dist.allreduce_sum) issues one
blocking, host-mediated collective per tensor AFTER the backward pass has
fully finished: gradient exchange serializes behind compute and per-tensor
launch overhead dominates on small params. This module is the fast path the
ROADMAP (item 4) calls for:

* the gradient pytree is partitioned into size-bounded, dtype-homogeneous
  **buckets** (``partition_buckets``), walked in *reverse production order*
  — the backward pass materializes the last layer's gradients first, so the
  first bucket closes while most of the backward graph is still pending;
* each bucket is flattened into ONE fused ``jax.lax.psum`` over the ``dp``
  mesh axis (``GradReducer.reduce``), *inside the traced step* — each
  collective's operands depend only on its own bucket's gradients, so XLA's
  latency-hiding scheduler is free to interleave the all-reduces with the
  remaining backward compute (the DepthController discipline from PR 3,
  generalized from host/device overlap to comm/compute overlap);
* the bucket size comes from the perfmodel interconnect table
  (``choose_bucket_bytes``): big enough that per-collective launch overhead
  is amortized below ``_LAUNCH_FRACTION`` of a bucket's transfer time,
  small enough that several buckets exist to overlap. ``MXNET_DDP_BUCKET_MB``
  overrides.

Wiring (enabled by ``MXNET_DDP=1`` / ``tools/launch.py --ddp``):
``module/fused.py`` wraps its step in ``shard_map`` over ``process_mesh()``
and reduces gradients through a ``GradReducer``; ``gluon/trainer.py`` and
the non-fused ``Module.update`` fall back to the eager
``dist.allreduce_tree`` (bucketed, but post-backward); ``parallel/spmd.py``
grows a ``ddp_bucketed`` mode composing the manual ``dp`` reduction with a
GSPMD-managed ``tp`` axis. The kvstore path remains for ``dist_async``.

MXL507 (analysis/hlo_passes.py) asserts the lowered step really does keep
the collectives interleavable; docs/distributed.md is the user guide.
"""
from __future__ import annotations

import numpy as _np

from .. import perfmodel as _perfmodel
from ..config import flags

__all__ = ["Bucket", "SparseBucket", "GradReducer", "enabled",
           "choose_bucket_bytes", "partition_buckets", "process_mesh",
           "estimate_overlap_ms", "to_global", "from_global"]

# A collective launch costs ~_LAUNCH_OVERHEAD_S on the host/ICI; size each
# bucket so that cost stays below _LAUNCH_FRACTION of its transfer time.
_LAUNCH_OVERHEAD_S = 20e-6
_LAUNCH_FRACTION = 0.05
_MIN_BUCKET_BYTES = 1 << 20    # 1 MiB: below this, launches dominate
_MAX_BUCKET_BYTES = 64 << 20   # 64 MiB: above this, overlap disappears


def enabled():
    """True when the bucketed DDP path is switched on (``MXNET_DDP=1``)."""
    return bool(flags.ddp)


def _device_kind():
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return _perfmodel.DEFAULT_DEVICE_KIND


def choose_bucket_bytes(device_kind=None):
    """Bucket size in bytes: ``MXNET_DDP_BUCKET_MB`` if set, else sized
    from the interconnect bandwidth so launch overhead amortizes to
    <= ``_LAUNCH_FRACTION`` of a bucket's transfer time, clamped to
    [1 MiB, 64 MiB]."""
    mb = float(flags.ddp_bucket_mb or 0.0)
    if mb > 0.0:
        return max(1, int(mb * (1 << 20)))
    bw = _perfmodel.interconnect_bytes_per_s(device_kind or _device_kind())
    raw = bw * _LAUNCH_OVERHEAD_S / _LAUNCH_FRACTION
    return int(min(max(raw, _MIN_BUCKET_BYTES), _MAX_BUCKET_BYTES))


class Bucket:
    """One fused all-reduce's worth of gradients (dtype-homogeneous)."""

    __slots__ = ("keys", "shapes", "sizes", "dtype", "nbytes")

    def __init__(self, entries):
        self.keys = tuple(k for k, _, _ in entries)
        self.shapes = tuple(tuple(s) for _, s, _ in entries)
        self.sizes = tuple(
            int(_np.prod(s, dtype=_np.int64)) if len(s) else 1
            for _, s, _ in entries)
        self.dtype = _np.dtype(entries[0][2])
        self.nbytes = sum(self.sizes) * self.dtype.itemsize

    def __repr__(self):
        return "Bucket(n=%d, dtype=%s, nbytes=%d)" % (
            len(self.keys), self.dtype.name, self.nbytes)


class SparseBucket:
    """One embedding gradient's sparse exchange plan.

    The dense path would all-reduce the full ``(rows, dim)`` gradient —
    almost entirely zeros when one step touches a few hundred of
    millions of rows. The sparse kind exchanges CONTRIBUTIONS instead:
    each rank all-gathers its ``(ids, values)`` pair (``length`` batch
    positions, duplicates included) and every rank coalesces the global
    set locally with a stable-sorted-id scatter-add. Comm volume is
    ``axis_size * length * (4 + dim*itemsize)`` vs ``rows*dim*itemsize``
    densified — orders of magnitude on real tables (the
    gradient-compression slot of PAPER.md capability 5).

    Determinism is the point, not a side effect: all_gather concatenates
    in rank order and the sort is STABLE, so each row's contributions
    fold in (rank, batch-position) order — bitwise-identical to the
    left fold a 1-rank dense VJP scatter-add performs over the same
    global batch. tests/test_embed.py pins both properties (>=10x bytes
    and bitwise-equal updates vs the 1-rank oracle)."""

    __slots__ = ("key", "length", "dim", "rows", "dtype")

    def __init__(self, key, length, dim, rows, dtype="float32"):
        self.key = key
        self.length = int(length)   # per-rank contribution count
        self.dim = int(dim)
        self.rows = int(rows)       # dense rows the grad densifies to
        self.dtype = _np.dtype(dtype)

    def comm_bytes(self, axis_size):
        """Gathered volume per device: ids (int32) + values."""
        return (self.length * axis_size
                * (4 + self.dim * self.dtype.itemsize))

    def densified_bytes(self):
        """What the dense bucket path would move for this grad."""
        return self.rows * self.dim * self.dtype.itemsize

    def __repr__(self):
        return ("SparseBucket(%r, L=%d, dim=%d, rows=%d)"
                % (self.key, self.length, self.dim, self.rows))


def coalesce_sparse_grad(ids, values, rows, axis_name=None):
    """Reduce one sparse gradient to its dense ``(rows, dim)`` form.

    ``ids``/``values`` are this rank's raw per-position contributions
    (any leading shape; flattened here). With ``axis_name`` (inside
    shard_map) the contributions are first all-gathered in rank order;
    the coalesce is then a stable sort by id + scatter-add — the
    sorted-id reduction order that makes the result independent of
    sharding, bit for bit. Traced, differentiable-free (gradient of a
    gradient is out of scope)."""
    import jax
    import jax.numpy as jnp
    dim = values.shape[-1]
    ids = ids.astype(jnp.int32).reshape(-1)
    values = values.reshape(-1, dim)
    if axis_name is not None:
        ids = jax.lax.all_gather(ids, axis_name, tiled=True)
        values = jax.lax.all_gather(values, axis_name, tiled=True)
    ids = jnp.clip(ids, 0, rows - 1)
    order = jnp.argsort(ids, stable=True)
    return (jnp.zeros((rows, dim), values.dtype)
            .at[ids[order]].add(values[order]))


def partition_buckets(entries, bucket_bytes=None, reverse=True):
    """Partition ``(key, shape, dtype)`` entries into size-bounded,
    dtype-homogeneous buckets.

    ``reverse=True`` (default) walks the entries back-to-front so bucket 0
    holds the *last* parameters' gradients — the ones the backward pass
    produces first, whose reduce can hide under the rest of the backward.
    A parameter larger than ``bucket_bytes`` gets a bucket of its own; a
    dtype change always closes the current bucket (mixed bf16/f32 grads
    never share a flat buffer).
    """
    bucket_bytes = bucket_bytes or choose_bucket_bytes()
    norm = [(k, tuple(s), _np.dtype(d)) for k, s, d in entries]
    if reverse:
        norm = norm[::-1]
    buckets, cur, cur_bytes = [], [], 0
    for key, shape, dtype in norm:
        n = int(_np.prod(shape, dtype=_np.int64)) if len(shape) else 1
        nbytes = n * dtype.itemsize
        if cur and (dtype != cur[0][2] or cur_bytes + nbytes > bucket_bytes):
            buckets.append(Bucket(cur))
            cur, cur_bytes = [], 0
        cur.append((key, shape, dtype))
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:
            buckets.append(Bucket(cur))
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(Bucket(cur))
    return buckets


class GradReducer:
    """Traced bucketed all-reduce over a named mesh axis.

    Built once per compiled step from the gradients' (name, shape, dtype)
    entries; ``reduce`` must be called inside a ``shard_map`` (or pmap)
    body that binds ``axis_name``. Host-side ``stats()`` never touches the
    device — it is the telemetry source for ``ddp/*`` counters.
    """

    def __init__(self, entries, axis_name=None, bucket_bytes=None,
                 axis_size=None, device_kind=None, sparse=None):
        self.axis_name = axis_name or flags.ddp_axis
        self.bucket_bytes = int(
            bucket_bytes or choose_bucket_bytes(device_kind))
        self.buckets = partition_buckets(entries, self.bucket_bytes)
        self.comm_bytes = sum(b.nbytes for b in self.buckets)
        self.axis_size = axis_size
        self._device_kind = device_kind
        # sparse bucket kind: {key: SparseBucket} — these keys travel as
        # (ids, values) contribution pairs, never as dense tensors
        self.sparse = {}
        for sb in (sparse or ()):
            if not isinstance(sb, SparseBucket):
                sb = SparseBucket(*sb)
            self.sparse[sb.key] = sb
        self.sparse_comm_bytes = sum(
            sb.comm_bytes(self.axis_size or 1)
            for sb in self.sparse.values())
        self.sparse_densified_bytes = sum(
            sb.densified_bytes() for sb in self.sparse.values())

    def reduce(self, grads):
        """Sum a ``{name: grad}`` dict over ``axis_name``, one fused psum
        per bucket, in reverse-production order. Traced; returns a dict
        with the same keys.

        Keys registered as sparse carry ``(ids, values)`` contribution
        pairs instead of dense arrays; they are exchanged with
        all_gather and coalesced in sorted-id order
        (:func:`coalesce_sparse_grad`) — the returned dict holds their
        DENSE ``(rows, dim)`` form, so optimizers downstream are
        oblivious to how the grad traveled."""
        import jax
        import jax.numpy as jnp
        out = {}
        for key, sb in self.sparse.items():
            if key not in grads:
                continue
            ids, values = grads[key]
            out[key] = coalesce_sparse_grad(
                ids, values, sb.rows,
                axis_name=self.axis_name if (self.axis_size or 1) > 1
                else None)
        for b in self.buckets:
            if len(b.keys) == 1:
                k = b.keys[0]
                out[k] = jax.lax.psum(grads[k], self.axis_name)
                continue
            flat = jnp.concatenate([jnp.ravel(grads[k]) for k in b.keys])
            flat = jax.lax.psum(flat, self.axis_name)
            off = 0
            for k, shape, size in zip(b.keys, b.shapes, b.sizes):
                out[k] = jax.lax.reshape(flat[off:off + size], shape)
                off += size
        return out

    def stats(self):
        """Host-held summary for telemetry/bench (zero device syncs)."""
        sizes = [b.nbytes for b in self.buckets]
        out = {
            "buckets": len(self.buckets),
            "bucket_bytes": sizes,
            # the interconnect-table policy value this reducer planned
            # against (MXNET_DDP_BUCKET_MB override included) — lets
            # dashboards and tests cross-check the plan against the ICI
            # table without re-deriving it
            "bucket_bytes_model": choose_bucket_bytes(self._device_kind),
            "bucket_bytes_plan": self.bucket_bytes,
            "comm_bytes": self.comm_bytes,
            "overlap_ms": estimate_overlap_ms(
                sizes, self.axis_size or 1, self._device_kind),
        }
        if self.sparse:
            out["sparse_buckets"] = len(self.sparse)
            out["sparse_comm_bytes"] = self.sparse_comm_bytes
            out["sparse_densified_bytes"] = self.sparse_densified_bytes
            if self.sparse_comm_bytes:
                out["sparse_compression"] = round(
                    self.sparse_densified_bytes
                    / self.sparse_comm_bytes, 3)
        return out


def estimate_overlap_ms(bucket_nbytes, axis_size, device_kind=None):
    """Model-estimated collective time hideable under backward compute:
    ring all-reduce transfer time of every bucket except the last to
    close (the first layers' gradients end the backward pass — nothing
    remains to overlap them with). Chip-free; used for the
    ``ddp/overlap_ms`` gauge and the bench ``overlap_frac``."""
    if axis_size <= 1 or len(bucket_nbytes) <= 1:
        return 0.0
    bw = _perfmodel.interconnect_bytes_per_s(device_kind or _device_kind())
    ring = 2.0 * (axis_size - 1) / axis_size
    return sum(ring * b / bw for b in bucket_nbytes[:-1]) * 1e3


_MESHES = {}


def process_mesh(axis_name=None):
    """The 1-D data-parallel mesh: EVERY addressable-or-not device in the
    process group, ordered by (process_index, id), on one ``dp`` axis.
    On a CPU test fleet that is one device per process; on a pod slice it
    is every chip. Cached per axis name (Mesh identity keys jit caches)."""
    axis_name = axis_name or flags.ddp_axis
    mesh = _MESHES.get(axis_name)
    if mesh is None:
        import jax
        from jax.sharding import Mesh
        devs = sorted(jax.devices(),
                      key=lambda d: (d.process_index, d.id))
        mesh = Mesh(_np.array(devs), (axis_name,))
        _MESHES[axis_name] = mesh
    return mesh


def to_global(value, mesh, spec):
    """Promote a process-local array to a global array on ``mesh`` with
    ``spec`` (the multi-host shard_map input contract). Leaves already on
    ``mesh`` pass through — after the first step the rebound params/opt
    state are global and must not be re-converted."""
    sharding = getattr(value, "sharding", None)
    if sharding is not None and getattr(sharding, "mesh", None) == mesh:
        return value
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        value, mesh, spec)


def from_global(value, mesh, spec):
    """Demote a global array back to this process's local view (the
    per-rank outputs the host metric/commit path consumes)."""
    from jax.experimental import multihost_utils
    return multihost_utils.global_array_to_host_local_array(
        value, mesh, spec)
