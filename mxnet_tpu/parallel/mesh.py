"""Mesh construction helpers."""
from __future__ import annotations

import numpy as _np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "dp_mesh", "data_parallel_sharding", "replicated",
           "P", "NamedSharding", "Mesh"]


def dp_mesh(devices):
    """1-D data-parallel mesh over `devices` (order-preserving, cached so
    executors/parameters/loaders built from the same context list share one
    Mesh object)."""
    return _dp_mesh_cached(tuple(devices))


import functools as _functools


@_functools.lru_cache(maxsize=64)
def _dp_mesh_cached(devices):
    return Mesh(_np.asarray(devices), ("dp",))


def make_mesh(axes, devices=None):
    """Create a Mesh from {axis: size}. Sizes may use -1 for 'rest'.

    Devices default to all accelerators, falling back to virtual CPU devices
    (the test strategy: 8 forced host devices stand in for an 8-chip slice).
    """
    if devices is None:
        try:
            devices = jax.devices("tpu")
        except RuntimeError:
            devices = []
        if not devices:
            try:
                devices = jax.devices("cpu")
            except RuntimeError:
                devices = jax.devices()
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    devs = _np.asarray(devices[:total]).reshape(sizes)
    return Mesh(devs, tuple(names))


def data_parallel_sharding(mesh, batch_axis=0, dp_axis="dp"):
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = dp_axis
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())
