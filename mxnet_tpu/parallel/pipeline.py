"""Pipeline parallelism over a ``pp`` mesh axis (GPipe schedule).

Reference role: the reference has no pipeline engine — model parallelism
there is manual ``group2ctx`` placement (refused loudly by this
framework). The TPU-native design is the scaling-book recipe: stage
parameters carry a leading stage axis sharded over ``pp``; inside
``shard_map`` every device runs the SAME program — a ``lax.scan`` over
``n_micro + n_stage - 1`` ticks in which each device applies its stage to
whatever activation it holds and ``ppermute``s the result to the next
device. Bubble fraction is the GPipe (S-1)/(T) overhead; increase
microbatches to amortize. Differentiable end to end (ppermute has a
transpose rule), so ``jax.grad`` of a pipelined loss is the data-parallel
gradient.

The stage function is arbitrary jax (one or more layers); see
tests/test_pipeline_moe.py and __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["make_pipeline", "stack_stage_params"]


def stack_stage_params(param_list, mesh=None, axis_name="pp"):
    """Stack per-stage pytrees into one pytree with a leading stage axis
    (sharded over ``axis_name`` when a mesh is given)."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
    if mesh is not None:
        def put(x):
            spec = P(axis_name, *([None] * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))
        stacked = jax.tree.map(put, stacked)
    return stacked


def make_pipeline(stage_fn, mesh, axis_name="pp", n_microbatch=None):
    """Build ``pipeline(stage_params, x) -> y`` running ``stage_fn`` as a
    GPipe pipeline over the mesh's ``axis_name`` dimension.

    * ``stage_fn(params_i, x) -> x`` — one stage's computation; every
      stage must map (micro_batch, d) -> (micro_batch, d_out) with a
      shape all stages share (the classic equal-width pipeline).
    * ``stage_params`` — pytree with leading axis ``n_stage`` (see
      stack_stage_params), sharded over ``axis_name``.
    * ``x`` — (batch, d); batch must divide into ``n_microbatch``.
    """
    from ._compat import shard_map_no_check

    n_stage = mesh.shape[axis_name]
    if n_microbatch is None:
        n_microbatch = n_stage

    def pipelined(stage_params, x):
        n_micro = n_microbatch
        if x.shape[0] % n_micro:
            raise ValueError(
                "pipeline batch %d must divide n_microbatch %d"
                % (x.shape[0], n_micro))
        micro = x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])

        # replication checker off: the psum-of-banked-zeros trick
        # confuses its static analysis (the result IS replicated)
        smap = shard_map_no_check(mesh=mesh,
                                  in_specs=(P(axis_name), P()),
                                  out_specs=P())

        @smap
        def run(params, micro_all):
            # params arrives with the leading stage axis sharded: this
            # device holds exactly its stage's slice, shape (1, ...)
            my_params = jax.tree.map(lambda p: p[0], params)
            stage = lax.axis_index(axis_name)
            right_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            T = n_micro + n_stage - 1
            mshape = micro_all.shape[1:]

            def tick(carry, t):
                buf, outs = carry
                # stage 0 ingests microbatch t (zeros once drained)
                feed = lax.dynamic_index_in_dim(
                    micro_all, jnp.minimum(t, n_micro - 1), 0,
                    keepdims=False)
                feed = jnp.where(t < n_micro, feed, jnp.zeros(mshape,
                                                              micro_all.dtype))
                inp = jnp.where(stage == 0, feed, buf)
                y = stage_fn(my_params, inp)
                # the LAST stage's output for microbatch m emerges at
                # tick t = m + n_stage - 1; bank it
                m = t - (n_stage - 1)
                outs = lax.cond(
                    m >= 0,
                    lambda o: lax.dynamic_update_index_in_dim(
                        o, jnp.where(stage == n_stage - 1, y,
                                     jnp.zeros_like(y)),
                        jnp.maximum(m, 0), 0),
                    lambda o: o, outs)
                # rotate activations one stage to the right
                buf = lax.ppermute(y, axis_name, right_perm)
                return (buf, outs), None

            buf0 = jnp.zeros(mshape, micro_all.dtype)
            outs0 = jnp.zeros((n_micro,) + mshape, micro_all.dtype)
            (_, outs), _ = lax.scan(tick, (buf0, outs0),
                                    jnp.arange(T))
            # every device banked zeros except the last stage: one psum
            # replicates the result
            return lax.psum(outs, axis_name)

        out = run(stage_params, micro)
        return out.reshape(x.shape[0], *out.shape[2:])

    return pipelined
