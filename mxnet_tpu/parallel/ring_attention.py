"""Long-context attention: blockwise (flash-style) single chip and ring
attention over a sequence-parallel mesh axis.

This is NEW TPU-first scope beyond the 2018-era reference (SURVEY.md §5
records the reference has no sequence/context parallelism), required for
long-context parity with modern frameworks:

* :func:`blockwise_attention` — online-softmax attention over KV blocks via
  ``lax.scan``: O(T) memory instead of O(T^2), XLA fuses the inner matmuls
  onto the MXU. This is the single-chip flash-attention pattern.
* :func:`ring_attention` — shard the sequence over a mesh axis ('sp');
  each step computes attention against the local KV shard then rotates the
  KV shards around the ring with ``ppermute`` (ICI neighbor exchange),
  accumulating with the same online softmax. Communication overlaps the
  next step's compute inside one compiled SPMD program.

Shapes follow (batch, heads, seq, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["blockwise_attention", "ring_attention", "attention_reference",
           "make_ring_attention"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal=False):
    """Dense O(T^2) reference attention (for tests)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _block_update(q, k_blk, v_blk, m, l, o, mask=None):
    """One online-softmax accumulation step.

    m: running rowmax (B,H,Tq,1); l: running denom; o: running numerator.
    Accumulators are float32 regardless of the input dtype (flash-attention
    discipline): in bf16 the -1e30 init saturates and low-precision
    accumulation loses accuracy; the QK/PV matmuls run on the MXU with f32
    accumulation via preferred_element_type.
    """
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) / math.sqrt(d)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0); use where
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_blk.dtype),
                                  v_blk,
                                  preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def blockwise_attention(q, k, v, block_size=512, causal=False):
    """Memory-efficient attention: scan over KV blocks (flash pattern)."""
    b, h, t, d = q.shape
    tk = k.shape[2]
    block_size = min(block_size, tk)
    n_blocks = (tk + block_size - 1) // block_size
    pad = n_blocks * block_size - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, n_blocks, block_size, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, n_blocks, block_size, d).transpose(2, 0, 1, 3, 4)
    q_pos = jnp.arange(t)[:, None]

    def step(carry, inputs):
        m, l, o = carry
        blk_idx, k_blk, v_blk = inputs
        kv_pos = blk_idx * block_size + jnp.arange(block_size)[None, :]
        mask = kv_pos < tk  # padding mask (Tq x block)
        if causal:
            mask = mask & (kv_pos <= q_pos + (tk - t))
        mask = mask[None, None]
        m, l, o = _block_update(q, k_blk, v_blk, m, l, o, mask)
        return (m, l, o), None

    m0 = jnp.full((b, h, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0),
                            (jnp.arange(n_blocks), kb, vb))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, axis_name="sp", causal=False):
    """Ring attention kernel body: call inside shard_map with q/k/v sharded
    on the sequence axis. Accumulates online softmax while rotating KV
    shards around the ring via ppermute."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape

    q_pos = my_idx * t_loc + jnp.arange(t_loc)[:, None]
    perm = [(j, (j + 1) % n) for j in range(n)]

    m = jnp.full(q[..., :1].shape, _NEG_INF, jnp.float32)
    l = jnp.zeros(q[..., :1].shape, jnp.float32)
    o = jnp.zeros(q.shape, jnp.float32)
    k_cur, v_cur = k, v
    # n is the static ring size, so unroll in python: each step attends to
    # the held KV shard then rotates it one ICI hop — except after the last
    # step, where the shards are back where they started and a final
    # rotation would be a wasted full-shard collective
    for s in range(n):
        # kv shard currently held: originally from device (my_idx - s) % n
        kv_idx = (my_idx - s) % n
        kv_pos = kv_idx * t_loc + jnp.arange(t_loc)[None, :]
        mask = (kv_pos <= q_pos)[None, None] if causal else None
        m, l, o = _block_update(q, k_cur, v_cur, m, l, o, mask)
        if s < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def make_ring_attention(mesh, axis_name="sp", causal=False):
    """Build a jitted ring-attention fn over `mesh`: inputs (B,H,T,D) are
    sharded on T over `axis_name`; output sharded the same way."""
    from ._compat import get_shard_map
    shard_map = get_shard_map()

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    jitted = jax.jit(fn)

    def run(q, k, v):
        sharding = NamedSharding(mesh, spec)
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
        return jitted(q, k, v)

    return run
