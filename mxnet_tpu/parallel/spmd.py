"""SPMD training step: forward+backward+allreduce+update in ONE XLA program.

This is the performance endgame the reference approaches with bulked engine
segments + kvstore reduce (SURVEY.md §3.3): here the whole training step —
including the gradient all-reduce that the reference routes through
CommDevice/RCCL/ps-lite — is a single jitted SPMD module over a device mesh.
GSPMD inserts the psum on ICI; the optimizer update (the reference's
optimizer ops) fuses into the same program, and parameter buffers are donated
so updates are in-place in HBM.

Sharding strategy:
* batch axis → 'dp' mesh axis (DataParallelExecutorGroup's slicing, done by
  GSPMD instead of python);
* optionally, large parameter matrices → 'tp' mesh axis (the reference's
  manual group2ctx model parallelism, done as tensor parallelism);
* everything else replicated.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..executor import _graph_eval_fn
from .. import random as _random

__all__ = ["SPMDTrainStep"]


def megatron_tp_rule(column_parallel=(), row_parallel=(), tp_axis="tp"):
    """Build a ``tp_rule`` implementing the Megatron-LM sharding pattern
    for FullyConnected weights (layout (out_features, in_features), the
    reference's FC layout — src/operator/nn/fully_connected-inl.h):

    * column-parallel layers (the FIRST matmul of an MLP pair, or the QKV
      projection of attention) split the OUTPUT dim: weight P(tp, None),
      bias P(tp). The activation comes out tp-sharded on features — no
      collective needed. NOTE for fused QKV: lay the output features out
      HEAD-MAJOR (reshape to (..., heads, 3, head_dim), not
      (..., 3, heads, head_dim)) so a contiguous row split is a whole-head
      partition; a 3-major interleave forces GSPMD to reshard at the
      downstream q/k/v split and costs extra all-gathers (numerics stay
      right, the one-psum-per-pair property doesn't).
    * row-parallel layers (the SECOND matmul / attention output proj)
      split the INPUT dim: weight P(None, tp), bias replicated. Consuming
      the tp-sharded activation needs one psum, which GSPMD inserts
      automatically at the sharding boundary.

    One collective per MLP/attention pair — the Megatron recipe — falls
    out of the two specs; nothing is hand-scheduled.

    ``column_parallel`` / ``row_parallel``: iterables of layer-name
    prefixes (e.g. ``["ffn1", "attn_qkv"]``; matches ``<prefix>_weight`` /
    ``<prefix>_bias``).
    """
    col = tuple(column_parallel)
    row = tuple(row_parallel)

    def rule(name, shape):
        for p in col:
            if name == p + "_weight" and len(shape) >= 2:
                return P(tp_axis, None)
            if name == p + "_bias":
                return P(tp_axis)
        for p in row:
            if name == p + "_weight" and len(shape) >= 2:
                return P(None, tp_axis)
            if name == p + "_bias":
                return P()   # replicated; added after the psum
        return None

    return rule


class SPMDTrainStep:
    """Compile a Symbol's training step over a mesh.

    step(params, aux, opt_state, data, label, key) ->
        (params, aux, opt_state, outputs)
    with SGD-momentum fused in (optimizer fusion = BASELINE MFU work item).
    """

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), dp_axis="dp", tp_axis=None,
                 lr=0.05, momentum=0.9, wd=0.0, rescale_grad=None,
                 tp_rule=None, dtype=None, ddp_bucketed=False,
                 bucket_bytes=None):
        self.symbol = symbol
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self._data_names = list(data_names)
        self._label_names = list(label_names)
        arg_names = symbol.list_arguments()
        inputs = set(self._data_names + self._label_names)
        self.param_names = [n for n in arg_names if n not in inputs]
        self.aux_names = symbol.list_auxiliary_states()
        eval_fn = _graph_eval_fn(symbol)
        self._eval_fn = eval_fn
        self.lr, self.momentum, self.wd = lr, momentum, wd
        self.rescale_grad = rescale_grad
        self.tp_rule = tp_rule or (lambda name, shape: None)

        dn, ln = self._data_names, self._label_names
        mom_coeff = momentum
        # Mixed precision (reference: multi-precision SGD,
        # python/mxnet/optimizer/optimizer.py:452): master weights stay
        # float32; compute runs in `dtype` (bf16 on the MXU). The cast sits
        # inside the differentiated function so grads come back f32. The
        # session dtype policy (config.compute_dtype) supplies/overrides
        # the default, same as the fused Module and Gluon paths.
        from .. import config as _config
        compute_dtype = _config.compute_dtype(default=dtype)

        def step(params, aux, opt_state, data, label, key):
            n_batch = data[dn[0]].shape[0]
            if self._reducer is not None:
                # manual-dp body: shapes are PER-SHARD — the mean must
                # still be over the global batch, and the psum'd gradient
                # is the global sum, so scale by local * dp_size (static)
                n_batch = n_batch * self._ddp_size
                # decorrelate per-shard dropout/noise deterministically
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(self.dp_axis))
            scale = (1.0 / n_batch) if rescale_grad is None else rescale_grad

            def loss_fn(p):
                if compute_dtype is not None:
                    p = {k: (v.astype(compute_dtype)
                             if v.dtype == jnp.float32 else v)
                         for k, v in p.items()}
                arg_vals = {**p, **data, **label}
                outs, auxu = eval_fn(arg_vals, aux, key, True)
                # loss heads (SoftmaxOutput etc.) carry custom VJPs seeded by
                # an all-ones cotangent — summing outputs reproduces the
                # reference's backward() seed exactly.
                total = 0.0
                for o in outs:
                    total = total + jnp.sum(o)
                return total, (outs, auxu)

            from ..executor import mirror_wrap
            grads, (outs, auxu) = jax.grad(mirror_wrap(loss_fn),
                                           has_aux=True)(params)
            if self._reducer is not None:
                # bucketed manual psum over dp (parallel/ddp.py): one
                # fused collective per bucket, in reverse-production
                # order, interleavable with the remaining backward.
                # tp-sharded params (GSPMD's auto axis) are reduced
                # per-param so their flat buffers never force a layout
                # change of the tp sharding.
                red = self._reducer.reduce(
                    {k: grads[k] for k in self._reducer_keys})
                for k in self._ddp_tp_names:
                    red[k] = jax.lax.psum(grads[k], self.dp_axis)
                grads = red
            new_params = {}
            new_opt = {}
            for k, w in params.items():
                g = grads[k] * scale + wd * w
                m = mom_coeff * opt_state[k] - lr * g
                new_opt[k] = m
                new_params[k] = w + m
            new_aux = {**aux, **auxu}
            return new_params, new_aux, new_opt, outs

        # shardings
        self._param_sharding = {}
        self._step = step
        self._jitted = None
        self._depth_ctl = None
        # bucketed-DDP mode: the dp gradient reduction becomes explicit
        # (shard_map + GradReducer) instead of GSPMD-inferred; built in
        # compile() where the param shapes are known
        self._ddp_bucketed = bool(ddp_bucketed)
        self._bucket_bytes = bucket_bytes
        self._reducer = None
        self._reducer_keys = frozenset()
        self._ddp_tp_names = ()
        self._ddp_size = int(mesh.shape[dp_axis]) if ddp_bucketed else 1

    def _shard_params(self, shapes):
        out = {}
        for name, shp in shapes.items():
            spec = None
            if self.tp_axis is not None:
                spec = self.tp_rule(name, shp)
            out[name] = NamedSharding(self.mesh, spec if spec is not None else P())
        return out

    def _build_reducer(self, param_shapes):
        """Split params into the bucketed-replicated set and the
        tp-sharded set (reduced per-param), then build the GradReducer
        over the replicated ones in forward order (it re-walks them in
        reverse-production order itself)."""
        from . import ddp as _ddp
        rep, tp_names = [], []
        for n in self.param_names:
            if n not in param_shapes:
                continue
            spec = self.tp_rule(n, param_shapes[n]) \
                if self.tp_axis is not None else None
            if spec is not None and tuple(spec) and \
                    any(ax is not None for ax in tuple(spec)):
                tp_names.append(n)
            else:
                rep.append((n, tuple(param_shapes[n]), _np.dtype(_np.float32)))
        self._reducer = _ddp.GradReducer(
            rep, axis_name=self.dp_axis, bucket_bytes=self._bucket_bytes,
            axis_size=self._ddp_size)
        self._reducer_keys = frozenset(e[0] for e in rep)
        self._ddp_tp_names = tuple(tp_names)

    def ddp_stats(self):
        """Host-held bucket plan summary (None unless ddp_bucketed)."""
        return self._reducer.stats() if self._reducer is not None else None

    def compile(self, param_shapes, aux_shapes, data_shapes, label_shapes):
        p_sh = self._shard_params(param_shapes)
        a_sh = {k: NamedSharding(self.mesh, P()) for k in aux_shapes}
        d_sh = {k: NamedSharding(self.mesh, P(self.dp_axis))
                for k in data_shapes}
        l_sh = {k: NamedSharding(self.mesh, P(self.dp_axis))
                for k in label_shapes}
        key_sh = NamedSharding(self.mesh, P())
        fn = self._step
        if self._ddp_bucketed:
            # explicit-collective mode: dp becomes a MANUAL mesh axis
            # (shard_map) so the bucketed psums in step() are real; any
            # other axes (tp) stay auto — GSPMD still places those.
            from jax.experimental.shard_map import shard_map
            self._build_reducer(param_shapes)
            auto = frozenset(a for a in self.mesh.axis_names
                             if a != self.dp_axis)
            d_spec = {k: P(self.dp_axis) for k in data_shapes}
            l_spec = {k: P(self.dp_axis) for k in label_shapes}
            p_spec = {k: P() for k in param_shapes}
            a_spec = {k: P() for k in aux_shapes}
            fn = shard_map(
                fn, mesh=self.mesh,
                in_specs=(p_spec, a_spec, p_spec, d_spec, l_spec, P()),
                out_specs=(p_spec, a_spec, p_spec, P(self.dp_axis)),
                check_rep=False, auto=auto)
        self._jitted = jax.jit(
            fn,
            in_shardings=(p_sh, a_sh, p_sh, d_sh, l_sh, key_sh),
            out_shardings=(p_sh, a_sh, p_sh, None),
            donate_argnums=(0, 1, 2))
        self._shardings = (p_sh, a_sh, d_sh, l_sh)
        return self._jitted

    def init(self, param_shapes, aux_shapes, seed=0):
        """Xavier-ish init placed with the right shardings."""
        rng = _np.random.RandomState(seed)
        p_sh, a_sh, _, _ = self._shardings
        params = {}
        for name, shp in param_shapes.items():
            if name.endswith("bias") or name.endswith("beta") or \
                    name.endswith("_mean"):
                v = _np.zeros(shp, _np.float32)
            elif name.endswith("gamma") or name.endswith("_var"):
                v = _np.ones(shp, _np.float32)
            else:
                fan = _np.prod(shp[1:]) if len(shp) > 1 else shp[0]
                v = rng.normal(0, _np.sqrt(2.0 / max(fan, 1)), shp).astype(_np.float32)
            params[name] = jax.device_put(v, p_sh[name])
        aux = {}
        for name, shp in aux_shapes.items():
            v = _np.ones(shp, _np.float32) if name.endswith("var") \
                else _np.zeros(shp, _np.float32)
            aux[name] = jax.device_put(v, a_sh[name])
        opt = {k: jax.device_put(_np.zeros(shp, _np.float32), p_sh[k])
               for k, shp in param_shapes.items()}
        return params, aux, opt

    def __call__(self, params, aux, opt_state, data, label, key=None):
        if key is None:
            key = _random.next_key()
        out = self._jitted(params, aux, opt_state, data, label, key)
        # async dispatch with bounded depth: the caller's loop keeps
        # enqueueing steps; block only once flags.engine_depth programs
        # are in flight (one output handle stands for the whole step)
        if self._depth_ctl is None:
            from ..engine import DepthController
            self._depth_ctl = DepthController()
        outs = out[3]
        self._depth_ctl.admit(list(outs)[:1] if outs else [])
        return out

    def quiesce(self):
        """Block until every in-flight SPMD step has retired."""
        if self._depth_ctl is not None:
            self._depth_ctl.quiesce()

    # -- elastic checkpointing ----------------------------------------------
    def save_checkpoint(self, manager, params, aux, opt_state, step,
                        epoch=0, nbatch=0, blocking=None):
        """Snapshot the SPMD training state through a CheckpointManager.

        Buffers are materialised to host numpy BEFORE handing off to the
        (possibly async) writer, so donation/in-place reuse of the device
        buffers by the next step can't race the save."""
        import pickle as _pickle
        self.quiesce()  # settle in-flight steps before materialising
        state = {}
        for k, v in params.items():
            state["arg:" + k] = _np.asarray(v)
        for k, v in aux.items():
            state["aux:" + k] = _np.asarray(v)
        for k, v in opt_state.items():
            state["opt:" + k] = _np.asarray(v)
        state["__rng__"] = _pickle.dumps(_random.get_state(), protocol=2)
        manager.save(state, step, epoch=epoch, nbatch=nbatch,
                     meta={"kvstore": "spmd"}, blocking=blocking)

    def restore_latest(self, manager, step=None):
        """Load the newest valid snapshot and place every buffer with the
        compiled shardings. Returns (params, aux, opt_state, manifest) or
        None. ``compile()`` must have run (the shardings come from it)."""
        import pickle as _pickle
        import jax as _jax
        state, manifest = manager.restore(step=step)
        if state is None:
            return None
        p_sh, a_sh, _, _ = self._shardings
        params, aux, opt = {}, {}, {}
        for k, v in state.items():
            if k == "__rng__":
                _random.set_state(_pickle.loads(bytes(v)))
            elif k.startswith("arg:"):
                params[k[4:]] = _jax.device_put(v, p_sh[k[4:]])
            elif k.startswith("aux:"):
                aux[k[4:]] = _jax.device_put(v, a_sh[k[4:]])
            elif k.startswith("opt:"):
                opt[k[4:]] = _jax.device_put(v, p_sh[k[4:]])
        return params, aux, opt, manifest
