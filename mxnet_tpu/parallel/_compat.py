"""JAX API compatibility shims shared by the parallel package."""
from __future__ import annotations

import inspect


def get_shard_map():
    """shard_map moved from jax.experimental to jax proper in 0.8."""
    try:
        from jax import shard_map  # JAX >= 0.8
    except ImportError:  # pragma: no cover - older JAX
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_no_check(**kwargs):
    """shard_map partial with the replication checker disabled — the
    kwarg was renamed check_rep -> check_vma across JAX versions."""
    import functools
    shard_map = get_shard_map()
    checker = "check_vma" if "check_vma" in \
        inspect.signature(shard_map).parameters else "check_rep"
    return functools.partial(shard_map, **{checker: False}, **kwargs)
