"""Multi-process distributed backend over ``jax.distributed``.

Reference analog: the ps-lite worker/server runtime —
``src/kvstore/kvstore_dist.h:44`` (worker push/pull RPCs),
``src/kvstore/kvstore_dist_server.h:155`` (server request handler), and the
process launcher ``tools/launch.py``.

TPU-native redesign (SURVEY.md §2.3/§7): there is no parameter server. The
PJRT coordination service provides rendezvous/liveness, and reductions ride
XLA collectives (ICI/DCN on TPU pods, Gloo on CPU test fleets). The
reference's server-side "aggregate then update once" becomes a symmetric
all-reduce with the optimizer update replicated on every worker — identical
arithmetic (every rank applies the same aggregated gradient to the same
replica), one hop fewer.

The *fast* path for multi-host training is not this module: it is the fused
SPMD train step over a global mesh (module/fused.py, parallel/spmd.py),
where GSPMD inserts the cross-host collectives inside the compiled program.
This module is the KVStore-compatibility path (``dist_sync``/``dist_async``)
and the process-group utility layer.

Environment (set by tools/launch.py; DMLC_* honored for reference parity):

=========================  ==============================  ================
purpose                    native name                     reference name
=========================  ==============================  ================
coordinator address        MXNET_COORDINATOR_ADDRESS       DMLC_PS_ROOT_URI
                                                           (+_PORT)
world size                 MXNET_NUM_WORKERS               DMLC_NUM_WORKER
process rank               MXNET_WORKER_RANK               DMLC_WORKER_ID
=========================  ==============================  ================
"""
from __future__ import annotations

import contextlib as _contextlib
import os

import numpy as _np

__all__ = ["init", "initialized", "rank", "num_workers", "barrier",
           "barrier_stats", "allreduce_sum", "allreduce_tree", "allgather",
           "broadcast", "env_spec"]

_INITIALIZED = False


def env_spec():
    """(coordinator, num_workers, rank) from the environment, or
    (None, None, None) when no launcher context is present."""
    addr = os.environ.get("MXNET_COORDINATOR_ADDRESS")
    if addr is None and os.environ.get("DMLC_PS_ROOT_URI"):
        addr = "%s:%s" % (os.environ["DMLC_PS_ROOT_URI"],
                          os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    n = os.environ.get("MXNET_NUM_WORKERS",
                       os.environ.get("DMLC_NUM_WORKER"))
    r = os.environ.get("MXNET_WORKER_RANK",
                       os.environ.get("DMLC_WORKER_ID"))
    return (addr,
            int(n) if n is not None else None,
            int(r) if r is not None else None)


def _externally_initialized():
    """True when the user bootstrapped jax.distributed themselves (the
    standard JAX multi-host recipe) — treat that as our process group.
    Checks the coordination client directly so probing does NOT initialize
    a backend."""
    try:
        from jax._src import distributed as _jd
        return _jd.global_state.client is not None
    except Exception:
        return False


def init(coordinator=None, num_workers_=None, rank_=None, strict=True):
    """Join the process group (idempotent). Arguments default to the
    launcher environment; an externally-initialized jax.distributed counts
    as joined; a no-launcher run is a 1-process group.

    strict=False (the import-time auto-join) quietly skips instead of
    raising on an incomplete/legacy environment — e.g. a reference-era
    ps-lite launcher exporting DMLC_PS_ROOT_URI to scheduler/server-role
    or rank-less processes; importing the library must not crash them.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    if _externally_initialized():
        _INITIALIZED = True
        # the prescribed multi-host mode: user-initialized jax.distributed
        # + MXNET_HEARTBEAT_DIR on a shared fs — liveness must beat here
        # too or every rank eventually looks dead to get_num_dead_node
        import jax
        from . import fault as _fault
        _fault.start(jax.process_index())
        return True
    role = os.environ.get("DMLC_ROLE")
    if role is not None and role != "worker":
        return False  # ps-lite scheduler/server processes never join
    env_addr, env_n, env_r = env_spec()
    coordinator = coordinator or env_addr
    num_workers_ = num_workers_ if num_workers_ is not None else env_n
    rank_ = rank_ if rank_ is not None else env_r
    if coordinator is None or not num_workers_ or num_workers_ <= 1:
        return False  # single-process: nothing to join
    if rank_ is None:
        if not strict:
            return False
        raise ValueError(
            "distributed launch is missing the worker rank: set "
            "MXNET_WORKER_RANK (or DMLC_WORKER_ID), or pass rank_=; "
            "every worker registering as rank 0 would hang the group")
    import jax
    try:
        # CPU test fleets need gloo cross-process collectives; must be
        # configured before the CPU backend client is created or every
        # collective dies with "Multiprocess computations aren't
        # implemented on the CPU backend"
        with _contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_workers_,
                                   process_id=rank_)
    except RuntimeError as e:
        raise RuntimeError(
            "jax.distributed must initialize before any JAX backend use. "
            "Import mxnet_tpu (or call mxnet_tpu.parallel.dist.init()) "
            "before creating arrays — under tools/launch.py the import "
            "does this automatically. Original error: %s" % e) from e
    _INITIALIZED = True
    from . import fault as _fault
    _fault.start(rank_)  # no-op unless the launcher provisioned a hb dir
    return True


def initialized():
    return _INITIALIZED or _externally_initialized()


def rank():
    if not initialized():
        return 0
    import jax
    return jax.process_index()


def num_workers():
    if not initialized():
        return 1
    import jax
    return jax.process_count()


# sync_global_devices builds (and caches) one tiny collective computation
# PER DISTINCT TAG STRING — callers minting per-step tags ("epoch3_batch42")
# grow the compile cache without bound. Tags are therefore folded onto a
# fixed slot pool with crc32 (deterministic across processes, unlike
# hash() under PYTHONHASHSEED); correctness only needs every rank to reach
# the same call site with the same tag, which maps to the same slot.
_BARRIER_SLOTS = 8
_BARRIER_TAGS = {}


def barrier(tag="mxnet_tpu_barrier"):
    """Block until every process reaches the same point (reference
    kvstore_dist.h Barrier RPC). Tags are batched onto a fixed slot pool
    — see ``barrier_stats()`` for the per-tag call census."""
    if not initialized():
        return
    import zlib
    from jax.experimental import multihost_utils
    _BARRIER_TAGS[tag] = _BARRIER_TAGS.get(tag, 0) + 1
    slot = zlib.crc32(tag.encode("utf-8")) % _BARRIER_SLOTS
    multihost_utils.sync_global_devices("mxnet_tpu_barrier_slot%d" % slot)


def barrier_stats():
    """{tag: call count} for this process — observability for the slot
    pool (which tag families are hot; all of them share _BARRIER_SLOTS
    compiled computations instead of one each)."""
    return dict(_BARRIER_TAGS)


def allreduce_sum(value):
    """Sum an array over all processes; every rank gets the result.

    A REAL compiled collective: one device per process forms a global
    ("p",) mesh, the per-process value becomes that process's shard of a
    global array, and a jitted sum over the sharded axis lowers to an XLA
    AllReduce riding DCN (Gloo on CPU fleets) — O(1) memory per rank and
    no host round-trip, unlike an allgather+host-sum (the reference's
    analog is the ps-lite server aggregation; kvstore_dist.h:44).
    """
    if not initialized():
        return value
    import jax
    import jax.numpy as jnp
    try:
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P
        mesh, fn = _reducer()
        v = jnp.asarray(value)
        garr = multihost_utils.host_local_array_to_global_array(
            v[None], mesh, P("p"))
        return fn(garr).addressable_data(0)
    except Exception:
        # defensive fallback (odd dtypes/backends): the gather path is
        # always correct, just not bandwidth-optimal
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(value)
        return jnp.asarray(gathered.sum(axis=0, dtype=gathered.dtype))


def allreduce_tree(tree, bucket_bytes=None):
    """Sum every leaf of a pytree over all processes with bucketed,
    dtype-coalesced collectives.

    The per-tensor ``allreduce_sum`` loop pays one host round-trip and one
    collective launch PER LEAF — launch overhead dominates on the many
    small params of a real net. This path flattens the leaves into
    size-bounded dtype-homogeneous buckets (``ddp.partition_buckets``, the
    same sizer the traced path uses) and issues ONE fused collective per
    bucket. It is the eager/non-traced fallback: the gradients are already
    materialized, so there is no backward left to overlap with — the win
    here is purely launch-count and per-call host overhead.
    """
    if not initialized():
        return tree
    import jax
    import jax.numpy as jnp
    from . import ddp as _ddp
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    arrs = [jnp.asarray(leaf) for leaf in leaves]
    entries = [(i, a.shape, a.dtype) for i, a in enumerate(arrs)]
    buckets = _ddp.partition_buckets(entries, bucket_bytes, reverse=False)
    out = [None] * len(arrs)
    for b in buckets:
        if len(b.keys) == 1:
            i = b.keys[0]
            out[i] = allreduce_sum(arrs[i])
            continue
        flat = jnp.concatenate([jnp.ravel(arrs[i]) for i in b.keys])
        red = jnp.asarray(allreduce_sum(flat))
        off = 0
        for i, shape, size in zip(b.keys, b.shapes, b.sizes):
            out[i] = red[off:off + size].reshape(shape)
            off += size
    return jax.tree_util.tree_unflatten(treedef, out)


_REDUCER = None


def _reducer():
    """(mesh, jitted sum-over-'p') — built ONCE per process: jax.jit's
    cache is keyed on function identity, so a fresh lambda per call would
    retrace and recompile on every gradient push."""
    global _REDUCER
    if _REDUCER is None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = _np.array([per_proc[p] for p in sorted(per_proc)])
        mesh = Mesh(devs, ("p",))
        fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                     in_shardings=NamedSharding(mesh, P("p")),
                     out_shardings=NamedSharding(mesh, P()))
        _REDUCER = (mesh, fn)
    return _REDUCER


def allgather(value):
    """Gather per-process arrays to every rank: returns (world, ...)."""
    if not initialized():
        import jax.numpy as jnp
        return jnp.asarray(value)[None]
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(value)


def broadcast(value, root=0):
    """Every rank receives `root`'s value (reference init-on-server)."""
    if not initialized():
        return value
    from jax.experimental import multihost_utils
    import jax.numpy as jnp
    v = jnp.asarray(value)
    if root == 0:
        # broadcast_one_to_all ignores non-root inputs (they only fix
        # shape/dtype); it hands back HOST numpy — convert, or the jax
        # NDArray methods (.at etc.) break downstream
        return jnp.asarray(multihost_utils.broadcast_one_to_all(v))
    return jnp.asarray(multihost_utils.process_allgather(v)[root])
