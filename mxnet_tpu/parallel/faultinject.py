"""Deterministic fault injection for elastic-training tests.

The reference framework's fault surface (ps-lite node death, dropped
connections, torn checkpoint writes) is only exercised in production;
this module makes every failure mode *reproducible* so the recovery
machinery (checkpoint/resume, launcher supervised restart, kvstore
client retry) can be tested on CPU with no chips and no flaky sleeps.

Faults are declared in the environment and fired at named injection
points inside the library::

    MXNET_FAULT_INJECT="kill@step=7:rank=0"

Grammar (comma-separated specs)::

    <action>@<point>=<match>[:key=val]...

Actions and their points:

``kill@step=N``
    SIGKILL the process when training step ``N`` begins (N steps have
    completed and been checkpointed).  Fired from ``Module.fit`` and
    ``gluon.Trainer.step``.  Options: ``rank=R`` (only that worker
    rank), ``sig=term`` (SIGTERM instead), ``rc=K`` (plain
    ``os._exit(K)``).
``delay@step=N:secs=S``
    Sleep ``S`` seconds (default 1.0) at step ``N`` — simulates a
    straggler so heartbeat/timeout knobs can be tuned in tests.
``conn_drop@call=OP[:count=K]``
    Drop the async-kvstore *client* connection before sending ``OP``
    (``pull``/``push``/...), ``K`` times (default 1).  Exercises the
    retry-with-backoff path in ``async_server.Client.call``.
``conn_drop@serve=OP[:count=K]``
    Same on the *server* side: the handler drops the connection when
    dispatching ``OP``.
``kill@ckpt=N`` / ``delay@ckpt=N``
    Fire between a checkpoint's data rename and its manifest rename —
    proves ``restore_latest`` ignores a data file with no manifest.
``truncate@ckpt=N[:bytes=B]``
    Corrupt the just-committed snapshot for step ``N`` by truncating
    ``B`` bytes (default 64) off its data file — proves the CRC check
    skips it.
``enospc@journal=OP``
    The storage fault model's "disk full": raise ``OSError(ENOSPC)``
    at a journal write site (``append``/``fsync``/``compact``).
    Unlimited by default — a full disk stays full until the spec is
    cleared, which is how the router's degraded-mode recovery
    (exit-without-restart) is tested.
``torn_write@journal=append[:bytes=B]``
    Power-loss semantics: the journal persists only the first ``B``
    bytes (default 6) of the record frame, then the append fails with
    ``OSError(EIO)``. Proves replay/replication tolerate a torn tail
    and that the writer repairs (truncates) it before appending again.
``slow_fsync@journal=fsync[:secs=S]``
    Sleep ``S`` seconds (default 0.05) inside the journal's fsync —
    a dying-disk straggler for group-commit latency tests.

Every spec accepts ``rank=R`` (matched against ``MXNET_WORKER_RANK``,
default 0), ``count=K`` (max number of firings; ``kill`` and
``conn_drop`` default to 1, everything else unlimited), and ``skip=N``
(ignore the first N matching occurrences before firing — e.g.
``kill@serve=decode_step:skip=6`` SIGKILLs a serving replica exactly 7
sampled tokens into a decode session, the deterministic mid-generation
death the fleet cursor-migration tests rely on).

The serving replicas expose two injection points on their hot paths:
``@serve=predict_batch`` (once per dispatched micro-batch) and
``@serve=decode_step`` (once per live decode step).

``tools/launch.py`` clears ``MXNET_FAULT_INJECT`` for restarted worker
incarnations, so an injected kill is a *first-run* event and the
supervised restart runs clean — which is exactly the recovery scenario
the tests assert.
"""
from __future__ import annotations

import errno
import logging
import os
import signal
import sys
import threading
import time

__all__ = ["fire", "specs", "reset", "InjectedFault", "InjectedConnDrop",
           "InjectedENOSPC", "InjectedTornWrite"]

_log = logging.getLogger("mxnet_tpu.faultinject")

_ACTIONS = ("kill", "delay", "conn_drop", "truncate", "raise",
            "enospc", "torn_write", "slow_fsync")

# point name -> the ctx key its @-match compares against
_POINT_MATCH_KEY = {"step": "step", "call": "op", "serve": "op",
                    "ckpt": "step", "journal": "op"}


class InjectedFault(RuntimeError):
    """Generic injected failure (action ``raise``)."""


class InjectedConnDrop(ConnectionError):
    """Injected connection drop — handled exactly like a real peer
    failure by both ends of the async kvstore protocol."""


class InjectedENOSPC(OSError):
    """Injected disk-full: an ``OSError`` with ``errno.ENOSPC``, so
    call sites that catch real storage failures catch this one the
    same way."""

    def __init__(self, point, raw):
        super().__init__(errno.ENOSPC,
                         "injected ENOSPC at %s (%r)" % (point, raw))


class InjectedTornWrite(OSError):
    """Injected torn write: the firing site must persist only the
    first ``keep_bytes`` of the payload it was about to write, then
    surface this as a failed write (``errno.EIO``)."""

    def __init__(self, keep_bytes, point, raw):
        super().__init__(errno.EIO,
                         "injected torn write at %s (%r)" % (point, raw))
        self.keep_bytes = int(keep_bytes)


class _Spec:
    __slots__ = ("action", "point", "match", "kwargs", "budget", "skip",
                 "raw")

    def __init__(self, action, point, match, kwargs, raw):
        self.action = action
        self.point = point
        self.match = match
        self.kwargs = kwargs
        self.raw = raw
        self.skip = int(kwargs.get("skip", 0))
        if "count" in kwargs:
            self.budget = int(kwargs["count"])
        elif action in ("kill", "conn_drop", "torn_write"):
            self.budget = 1
        else:
            # enospc deliberately unlimited: a full disk stays full
            # until the operator clears it (spec removed from the env)
            self.budget = -1  # unlimited

    def matches(self, ctx):
        key = _POINT_MATCH_KEY.get(self.point, self.point)
        if self.match != "" and str(ctx.get(key)) != self.match:
            return False
        want_rank = self.kwargs.get("rank")
        if want_rank is not None:
            have = os.environ.get("MXNET_WORKER_RANK",
                                  os.environ.get("DMLC_WORKER_ID", "0"))
            if str(want_rank) != str(have):
                return False
        return True


_lock = threading.Lock()
_cache_env = None
_cache_specs = ()


def _parse(text):
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        action, sep, rest = part.partition("@")
        action = action.strip()
        if not sep or action not in _ACTIONS:
            _log.warning("MXNET_FAULT_INJECT: ignoring malformed spec %r "
                         "(want <action>@<point>=<match>[:k=v...], "
                         "actions: %s)", part, "/".join(_ACTIONS))
            continue
        toks = rest.split(":")
        point, _, match = toks[0].partition("=")
        kwargs = {}
        ok = True
        for t in toks[1:]:
            k, eq, v = t.partition("=")
            if not eq:
                _log.warning("MXNET_FAULT_INJECT: ignoring malformed "
                             "option %r in spec %r", t, part)
                ok = False
                break
            kwargs[k.strip()] = v.strip()
        if ok:
            out.append(_Spec(action, point.strip(), match.strip(),
                             kwargs, part))
    return tuple(out)


def specs():
    """Parsed specs for the current MXNET_FAULT_INJECT value (cached per
    value, so monkeypatching the env between tests just works)."""
    global _cache_env, _cache_specs
    env = os.environ.get("MXNET_FAULT_INJECT", "")
    with _lock:
        if env != _cache_env:
            _cache_env = env
            _cache_specs = _parse(env) if env else ()
        return _cache_specs


def reset():
    """Drop the parse cache and firing budgets (test isolation)."""
    global _cache_env, _cache_specs
    with _lock:
        _cache_env = None
        _cache_specs = ()


def _consume(spec):
    with _lock:
        if spec.skip > 0:
            spec.skip -= 1
            return False
        if spec.budget == 0:
            return False
        if spec.budget > 0:
            spec.budget -= 1
        return True


def fire(point, **ctx):
    """Evaluate the injection specs at a named point.

    Call sites pass the point name plus whatever context the grammar can
    match on (``step=``, ``op=``, ``path=``...).  No-op (a dict lookup
    and an env compare) unless MXNET_FAULT_INJECT is set.
    """
    sps = specs()
    if not sps:
        return
    for sp in sps:
        if sp.point != point or not sp.matches(ctx) or not _consume(sp):
            continue
        _apply(sp, point, ctx)


def _apply(sp, point, ctx):
    _log.warning("fault injection: firing %r at point %r (ctx %r)",
                 sp.raw, point, ctx)
    if sp.action == "kill":
        # flight-recorder postmortem BEFORE the process vanishes: the
        # default kill is SIGKILL (uncatchable), so this is the only
        # chance to leave an artifact (no-op unless MXNET_TELEMETRY_DIR
        # is set; tools/fault_drill.py asserts the artifact). Best
        # effort — a telemetry bug must not turn a clean injected kill
        # into a different death.
        try:
            from ..telemetry import recorder as _trec
            rec = _trec.flight_recorder()
            rec.record_event("fault", point=point, spec=sp.raw,
                             ctx={k: str(v) for k, v in ctx.items()})
            rec.dump("faultinject: %s" % sp.raw)
        except Exception:
            pass
        # make the death observable in streamed launcher logs before the
        # process vanishes mid-write
        sys.stdout.flush()
        sys.stderr.flush()
        if "rc" in sp.kwargs:
            os._exit(int(sp.kwargs["rc"]))
        sig = signal.SIGTERM if sp.kwargs.get("sig") == "term" \
            else signal.SIGKILL
        os.kill(os.getpid(), sig)
        time.sleep(60)  # SIGKILL delivery is not synchronous
    elif sp.action == "delay":
        time.sleep(float(sp.kwargs.get("secs", 1.0)))
    elif sp.action == "conn_drop":
        raise InjectedConnDrop(
            "injected connection drop at %s (%r)" % (point, sp.raw))
    elif sp.action == "truncate":
        path = ctx.get("path")
        if path and os.path.exists(path):
            nbytes = int(sp.kwargs.get("bytes", 64))
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(0, size - nbytes))
    elif sp.action == "raise":
        raise InjectedFault("injected fault at %s (%r)" % (point, sp.raw))
    elif sp.action == "enospc":
        raise InjectedENOSPC(point, sp.raw)
    elif sp.action == "torn_write":
        raise InjectedTornWrite(int(sp.kwargs.get("bytes", 6)),
                                point, sp.raw)
    elif sp.action == "slow_fsync":
        time.sleep(float(sp.kwargs.get("secs", 0.05)))
