"""Versioned parameter-layout manifests: the single source of truth for
*where every parameter shard lives* in an N-way run.

The elastic-fleet contract (ROADMAP item 3) needs three consumers to
agree on one description of a parameter layout:

* **Checkpoint restore** — a run killed at world N must be resumable at
  world N−k (or N+k): gather each parameter from the old layout, re-slice
  per the new one, carry optimizer/RNG state along
  (:func:`mxnet_tpu.checkpoint.reshard_checkpoint` /
  ``CheckpointManager.restore_resharded``).
* **Artifact export** — ``serving.reshard_artifact`` re-targets a
  ``.mxtpu`` export to a different inference mesh; the manifest records
  the layout the artifact was exported under.
* **Fleet registry** — each replica registers its layout fingerprint so
  the router can refuse mixed-layout traffic splits (a hop cursor is
  only portable between replicas that agree on the layout).

A manifest is a plain JSON-able dict: schema version, world size, and a
``key -> entry`` map where an entry is either ``replicated`` (every rank
holds the full array) or ``sharded`` (contiguous blocks along one axis,
``parts`` listing each rank's ``[rank, start, stop]`` row range).
``fingerprint()`` hashes the canonical form the same way the
kernel-tuning cache does (``tune/cache.py``), so two processes can agree
on "same layout" with a 12-hex string instead of shipping the map.

Deliberately import-light (numpy + stdlib): the router and the CLI tools
must be able to reason about layouts without paying a jax import.
"""
from __future__ import annotations

import hashlib
import json

import numpy as _np

__all__ = ["LayoutManifest", "partition", "gather_state", "shard_state",
           "reshard_states", "infer_manifest", "SCHEMA_VERSION"]

FORMAT = "mxtpu-layout"
SCHEMA_VERSION = 1

# state-dict keys that are opaque per-run blobs, not arrays: they ride
# the reshard replicated (every new rank gets rank 0's copy) because the
# training math they feed is world-size invariant by the DDP contract
# (fixed global batch, replicated params, seed-derived RNG chains)
_BLOB_KEYS = ("__opt__", "__rng__")
# the data cursor is rank/world-fingerprinted (PR-18: a foreign seek
# raises) — it is DROPPED across a world change; the resumed run starts
# a fresh epoch at the checkpointed step
_DROP_KEYS = ("__data_cursor__",)


def partition(n, world):
    """Contiguous near-even split of ``n`` rows over ``world`` ranks:
    ``[(start, stop), ...]`` with the remainder spread over the leading
    ranks (the same arithmetic everywhere, so two processes computing a
    layout independently always agree). Ranks past ``n`` get empty
    ``(n, n)`` slices — a 3-row table on 5 hosts is legal, just idle."""
    n, world = int(n), int(world)
    if world <= 0:
        raise ValueError("layout: world must be >= 1, got %d" % world)
    base, rem = divmod(n, world)
    bounds = []
    start = 0
    for r in range(world):
        stop = start + base + (1 if r < rem else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


class LayoutManifest:
    """Param -> shard map at one world size, fingerprinted + versioned.

    ``entries`` maps a state-dict key to either
    ``{"kind": "replicated", "shape": [...]}`` or
    ``{"kind": "sharded", "axis": a, "shape": [...global...],
    "parts": [[rank, start, stop], ...]}``.
    """

    def __init__(self, world, entries, mesh=None,
                 schema_version=SCHEMA_VERSION):
        self.world = int(world)
        self.entries = dict(entries)
        self.mesh = dict(mesh or {})
        self.schema_version = int(schema_version)

    # -- construction --------------------------------------------------------
    @classmethod
    def build(cls, shapes, world, sharded_axes=None, mesh=None):
        """Manifest over ``shapes`` (key -> global shape): every key is
        replicated unless ``sharded_axes`` names its shard axis."""
        sharded_axes = dict(sharded_axes or {})
        entries = {}
        for key, shape in shapes.items():
            shape = [int(d) for d in shape]
            axis = sharded_axes.get(key)
            if axis is None:
                entries[key] = {"kind": "replicated", "shape": shape}
            else:
                axis = int(axis)
                if not 0 <= axis < len(shape):
                    raise ValueError(
                        "layout: shard axis %d out of range for %r "
                        "shape %s" % (axis, key, shape))
                parts = [[r, s, e] for r, (s, e)
                         in enumerate(partition(shape[axis], world))]
                entries[key] = {"kind": "sharded", "axis": axis,
                                "shape": shape, "parts": parts}
        return cls(world, entries, mesh=mesh)

    @classmethod
    def replicated(cls, shapes, world, mesh=None):
        """All-replicated manifest (the DDP layout)."""
        return cls.build(shapes, world, sharded_axes=None, mesh=mesh)

    def reshard_to(self, new_world):
        """The same logical layout re-partitioned for ``new_world``:
        replicated entries stay replicated, sharded entries get fresh
        contiguous parts over the new rank count."""
        entries = {}
        for key, e in self.entries.items():
            if e["kind"] == "replicated":
                entries[key] = dict(e)
            else:
                axis = int(e["axis"])
                shape = list(e["shape"])
                parts = [[r, s, t] for r, (s, t)
                         in enumerate(partition(shape[axis], new_world))]
                entries[key] = {"kind": "sharded", "axis": axis,
                                "shape": shape, "parts": parts}
        return LayoutManifest(new_world, entries, mesh=self.mesh,
                              schema_version=self.schema_version)

    # -- identity ------------------------------------------------------------
    def fingerprint(self):
        """Short stable hash of schema+world+entries — what a fleet
        replica registers under and the router compares across a split
        (mirrors ``tune/cache.Cache.fingerprint``)."""
        h = hashlib.sha256()
        h.update(("%s/%d/%d" % (FORMAT, self.schema_version,
                                self.world)).encode())
        h.update(json.dumps(self.mesh, sort_keys=True).encode())
        for k in sorted(self.entries):
            h.update(k.encode())
            h.update(json.dumps(self.entries[k], sort_keys=True).encode())
        return h.hexdigest()[:12]

    # -- (de)serialization ---------------------------------------------------
    def to_dict(self):
        return {
            "format": FORMAT,
            "schema_version": self.schema_version,
            "world": self.world,
            "mesh": dict(self.mesh),
            "entries": {k: dict(v) for k, v in self.entries.items()},
            "fingerprint": self.fingerprint(),
        }

    @classmethod
    def from_dict(cls, d):
        if not isinstance(d, dict) or d.get("format") != FORMAT:
            raise ValueError("layout: not a %s manifest: %r"
                             % (FORMAT, type(d).__name__))
        man = cls(d["world"], d.get("entries") or {},
                  mesh=d.get("mesh"),
                  schema_version=d.get("schema_version", SCHEMA_VERSION))
        man.validate()
        return man

    def validate(self):
        """Structural check: sharded parts must tile [0, shape[axis])
        contiguously in rank order. Returns self."""
        for key, e in self.entries.items():
            kind = e.get("kind")
            if kind == "replicated":
                continue
            if kind != "sharded":
                raise ValueError("layout: entry %r has unknown kind %r"
                                 % (key, kind))
            axis, shape = int(e["axis"]), list(e["shape"])
            parts = e.get("parts") or []
            if len(parts) != self.world:
                raise ValueError(
                    "layout: entry %r has %d parts for world %d"
                    % (key, len(parts), self.world))
            cursor = 0
            for r, (rank, start, stop) in enumerate(parts):
                if rank != r or start != cursor or stop < start:
                    raise ValueError(
                        "layout: entry %r parts are not a contiguous "
                        "rank-ordered tiling (part %d = %s)"
                        % (key, r, parts[r]))
                cursor = stop
            if cursor != shape[axis]:
                raise ValueError(
                    "layout: entry %r parts cover %d of %d rows"
                    % (key, cursor, shape[axis]))
        return self

    # -- per-key geometry ----------------------------------------------------
    def part_for(self, key, rank):
        """(start, stop) of ``rank``'s block of ``key`` (replicated
        entries span the full leading axis)."""
        e = self.entries[key]
        if e["kind"] == "replicated":
            return 0, int(e["shape"][0]) if e["shape"] else 0
        rank = int(rank)
        _, start, stop = e["parts"][rank]
        return int(start), int(stop)

    def shard_array(self, key, rank, full):
        """``rank``'s slice of the global array ``full`` for ``key``."""
        e = self.entries.get(key)
        if e is None or e["kind"] == "replicated":
            return full
        axis = int(e["axis"])
        start, stop = self.part_for(key, rank)
        index = [slice(None)] * _np.ndim(full)
        index[axis] = slice(start, stop)
        return full[tuple(index)]


def infer_manifest(state, world, mesh=None):
    """Fallback manifest for a checkpoint that predates layout metadata
    (or whose layout record was corrupted): every array key is assumed
    REPLICATED — exactly the DDP contract every training path in this
    repo upholds. Blob keys (optimizer/RNG/cursor) are never manifest
    entries; they are handled by name in :func:`reshard_states`."""
    shapes = {k: list(_np.shape(v)) for k, v in state.items()
              if not isinstance(v, (bytes, bytearray))
              and not k.startswith("__")}
    return LayoutManifest.replicated(shapes, world, mesh=mesh)


def gather_state(states_by_rank, manifest):
    """Reassemble the GLOBAL state dict from per-rank state dicts
    (``{rank: state}``) laid out per ``manifest``: replicated keys come
    from the lowest present rank, sharded keys concatenate their parts
    in rank order. Blob keys are taken from the lowest rank. Raises
    ``KeyError`` when a rank a sharded entry needs is missing."""
    if not states_by_rank:
        raise ValueError("layout: no rank states to gather")
    ranks = sorted(states_by_rank)
    first = states_by_rank[ranks[0]]
    out = {}
    for key, value in first.items():
        if key in _DROP_KEYS:
            continue
        e = manifest.entries.get(key)
        if e is None or e["kind"] == "replicated" \
                or isinstance(value, (bytes, bytearray)):
            out[key] = value
            continue
        axis = int(e["axis"])
        blocks = []
        for rank, start, stop in e["parts"]:
            if stop <= start:
                continue
            if rank not in states_by_rank:
                raise KeyError(
                    "layout: gather of %r needs rank %d's shard but no "
                    "state for that rank was given" % (key, rank))
            blocks.append(_np.asarray(states_by_rank[rank][key]))
        out[key] = (blocks[0] if len(blocks) == 1
                    else _np.concatenate(blocks, axis=axis))
    return out


def shard_state(full_state, manifest, rank):
    """One rank's state dict, sliced out of the global ``full_state``
    per ``manifest``. Blob keys pass through whole."""
    out = {}
    for key, value in full_state.items():
        if key in _DROP_KEYS:
            continue
        if isinstance(value, (bytes, bytearray)):
            out[key] = value
        else:
            out[key] = manifest.shard_array(key, rank, _np.asarray(value))
    return out


def reshard_states(states_by_rank, manifest, new_world):
    """Gather per-rank checkpoint states from ``manifest``'s layout and
    re-slice them for ``new_world`` ranks.

    Returns ``(states_by_new_rank, new_manifest)``. Optimizer and RNG
    blobs are carried replicated (rank 0's copy — valid because the
    training math is world-size invariant: fixed global batch,
    replicated dense params, seed-derived RNG chains). The data cursor
    is dropped: PR-18 cursors fingerprint (rank, world, seed) and a
    resharded resume starts a fresh pass at the restored step."""
    full = gather_state(states_by_rank, manifest)
    new_manifest = manifest.reshard_to(new_world)
    out = {r: shard_state(full, new_manifest, r)
           for r in range(int(new_world))}
    try:
        from .. import telemetry as _telemetry
        _telemetry.counter(
            "layout/reshards_total",
            "State resharding operations (checkpoint or artifact)").inc()
        _telemetry.gauge(
            "layout/last_world",
            "World size the last reshard targeted").set(int(new_world))
        _telemetry.flight_recorder().record_event(
            "layout_reshard", old_world=manifest.world,
            new_world=int(new_world),
            fingerprint=new_manifest.fingerprint())
    except Exception:
        pass
    return out, new_manifest
