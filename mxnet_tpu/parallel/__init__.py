"""Parallelism over device meshes.

This layer replaces the reference's entire distribution stack
(src/kvstore/comm.h device reduce, comm_tree.h topology trees,
kvstore_nccl.h RCCL, kvstore_dist.h ps-lite — SURVEY.md §2.3) with
XLA-native SPMD: pick a `jax.sharding.Mesh`, annotate shardings, let GSPMD
insert collectives over ICI/DCN.
"""
from .mesh import make_mesh, data_parallel_sharding, replicated
from .spmd import SPMDTrainStep, megatron_tp_rule
from .pipeline import make_pipeline, stack_stage_params
from .moe import (moe_layer, init_moe_params, shard_moe_params,
                  aux_load_balance_loss)
from .ring_attention import (blockwise_attention, ring_attention,
                             make_ring_attention, attention_reference)
from ..ops.pallas_flash import flash_attention
from .layout import LayoutManifest
from . import ddp
from . import dist
from . import fault
from . import layout
