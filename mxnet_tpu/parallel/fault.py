"""Heartbeat-based failure detection (parity: ps-lite scheduler
heartbeats surfaced through ``KVStore::num_dead_node``,
include/mxnet/kvstore.h:353; ps-lite van heartbeat loop).

Design for the TPU runtime: PJRT's coordination service already fails
collectives when a host dies, but that failure is an exception at an
arbitrary collective — the reference instead exposes liveness as a
queryable surface so training loops (and the launcher) can react before
wedging.  Here every worker touches a per-rank heartbeat file under a
shared directory on a background thread; ``dead_nodes`` reports ranks
whose heartbeat is stale.  The single-host N-process launcher provisions
the directory (``MXNET_HEARTBEAT_DIR``); multi-host deployments point it
at a shared filesystem or rely on the coordination-service failure, which
the same API reports via ``barrier_healthy``.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["start", "stop", "dead_nodes", "heartbeat_dir", "active"]

_DEFAULT_INTERVAL = 1.0

_lock = threading.Lock()
_thread = None
_stop_evt = None
_started_at = None          # monotonic; see dead_nodes
# Liveness bookkeeping for dead_nodes: (dir, rank) -> [stat signature,
# monotonic stamp of the last observed change]. Staleness is judged on
# the MONOTONIC clock from the moment *this* process last saw the file
# change — a wall-clock step (NTP slew, manual date set) between polls
# can no longer mass-kill a healthy fleet. The wall/mtime delta is
# trusted exactly once, at first sight of a file, so a tracker that
# starts late still detects an already-stale heartbeat immediately.
_obs = {}


def heartbeat_dir():
    return os.environ.get("MXNET_HEARTBEAT_DIR") or None


def _hb_path(dir_, rank):
    return os.path.join(dir_, "hb_%d" % rank)


def _interval():
    try:
        return float(os.environ.get("MXNET_HEARTBEAT_INTERVAL",
                                    _DEFAULT_INTERVAL))
    except ValueError:
        return _DEFAULT_INTERVAL


def active():
    return _thread is not None and _thread.is_alive()


def start(rank, dir_=None, interval=None):
    """Begin heartbeating as ``rank`` (idempotent). No-op without a
    heartbeat directory."""
    global _thread, _stop_evt, _started_at
    dir_ = dir_ or heartbeat_dir()
    if dir_ is None:
        return False
    with _lock:
        if active():
            return True
        os.makedirs(dir_, exist_ok=True)
        interval = interval or _interval()
        _stop_evt = threading.Event()
        _started_at = time.monotonic()
        path = _hb_path(dir_, rank)

        def beat(evt=_stop_evt):
            # atomic write (temp + rename): dead_nodes readers and crash
            # forensics must never observe a partial "pid time" record
            tmp = path + ".tmp.%d" % os.getpid()
            while not evt.is_set():
                try:
                    with open(tmp, "w") as f:
                        f.write("%d %f" % (os.getpid(), time.time()))
                    os.replace(tmp, path)
                except OSError:
                    pass  # a vanished dir must not kill the worker
                evt.wait(interval)

        _thread = threading.Thread(target=beat, daemon=True,
                                   name="mxtpu-heartbeat")
        _thread.start()
    return True


def stop():
    """Stop heartbeating and JOIN the beat thread, so a test reusing the
    tmpdir can't race a straggler writing one last heartbeat."""
    global _thread, _stop_evt
    with _lock:
        t, _thread = _thread, None
        if _stop_evt is not None:
            _stop_evt.set()
        _stop_evt = None
        _obs.clear()
    if t is not None and t.is_alive():
        t.join(timeout=10.0)


def dead_nodes(num_workers, timeout=60.0, dir_=None):
    """Ranks considered dead: heartbeat file unchanged for > ``timeout``
    seconds of MONOTONIC time since this process last saw it change, or
    never written although the group has been up longer than ``timeout``
    (startup grace period).  Wall-clock enters the verdict only at first
    sight of a file (how stale was it when we arrived?); after that a
    rank stays alive iff its heartbeat keeps changing, so an NTP step or
    operator ``date`` call between polls cannot mass-kill the fleet."""
    dir_ = dir_ or heartbeat_dir()
    if dir_ is None or not os.path.isdir(dir_):
        return []
    mono_now = time.monotonic()
    up_since = _started_at if _started_at is not None else mono_now
    dead = []
    for r in range(num_workers):
        path = _hb_path(dir_, r)
        try:
            st = os.stat(path)
        except OSError:
            _obs.pop((dir_, r), None)   # reappearance = fresh sighting
            if mono_now - up_since > timeout:
                dead.append(r)
            continue
        sig = (st.st_mtime_ns, st.st_size)
        rec = _obs.get((dir_, r))
        if rec is None:
            # first sighting: trust the wall/mtime delta once, so an
            # already-stale file is dead immediately (a future mtime —
            # writer clock ahead of ours — clamps to "fresh")
            age = max(0.0, time.time() - st.st_mtime)
            rec = _obs[(dir_, r)] = [sig, mono_now - age]
        elif rec[0] != sig:
            rec[0] = sig
            rec[1] = mono_now
        if mono_now - rec[1] > timeout:
            dead.append(r)
    return dead
