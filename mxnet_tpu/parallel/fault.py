"""Heartbeat-based failure detection (parity: ps-lite scheduler
heartbeats surfaced through ``KVStore::num_dead_node``,
include/mxnet/kvstore.h:353; ps-lite van heartbeat loop).

Design for the TPU runtime: PJRT's coordination service already fails
collectives when a host dies, but that failure is an exception at an
arbitrary collective — the reference instead exposes liveness as a
queryable surface so training loops (and the launcher) can react before
wedging.  Here every worker touches a per-rank heartbeat file under a
shared directory on a background thread; ``dead_nodes`` reports ranks
whose heartbeat is stale.  The single-host N-process launcher provisions
the directory (``MXNET_HEARTBEAT_DIR``); multi-host deployments point it
at a shared filesystem or rely on the coordination-service failure, which
the same API reports via ``barrier_healthy``.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["start", "stop", "dead_nodes", "heartbeat_dir", "active"]

_DEFAULT_INTERVAL = 1.0

_lock = threading.Lock()
_thread = None
_stop_evt = None
_started_at = None


def heartbeat_dir():
    return os.environ.get("MXNET_HEARTBEAT_DIR") or None


def _hb_path(dir_, rank):
    return os.path.join(dir_, "hb_%d" % rank)


def _interval():
    try:
        return float(os.environ.get("MXNET_HEARTBEAT_INTERVAL",
                                    _DEFAULT_INTERVAL))
    except ValueError:
        return _DEFAULT_INTERVAL


def active():
    return _thread is not None and _thread.is_alive()


def start(rank, dir_=None, interval=None):
    """Begin heartbeating as ``rank`` (idempotent). No-op without a
    heartbeat directory."""
    global _thread, _stop_evt, _started_at
    dir_ = dir_ or heartbeat_dir()
    if dir_ is None:
        return False
    with _lock:
        if active():
            return True
        os.makedirs(dir_, exist_ok=True)
        interval = interval or _interval()
        _stop_evt = threading.Event()
        _started_at = time.time()
        path = _hb_path(dir_, rank)

        def beat(evt=_stop_evt):
            # atomic write (temp + rename): dead_nodes readers and crash
            # forensics must never observe a partial "pid time" record
            tmp = path + ".tmp.%d" % os.getpid()
            while not evt.is_set():
                try:
                    with open(tmp, "w") as f:
                        f.write("%d %f" % (os.getpid(), time.time()))
                    os.replace(tmp, path)
                except OSError:
                    pass  # a vanished dir must not kill the worker
                evt.wait(interval)

        _thread = threading.Thread(target=beat, daemon=True,
                                   name="mxtpu-heartbeat")
        _thread.start()
    return True


def stop():
    """Stop heartbeating and JOIN the beat thread, so a test reusing the
    tmpdir can't race a straggler writing one last heartbeat."""
    global _thread, _stop_evt
    with _lock:
        t, _thread = _thread, None
        if _stop_evt is not None:
            _stop_evt.set()
        _stop_evt = None
    if t is not None and t.is_alive():
        t.join(timeout=10.0)


def dead_nodes(num_workers, timeout=60.0, dir_=None):
    """Ranks considered dead: heartbeat file stale by > ``timeout``
    seconds, or never written although the group has been up longer than
    ``timeout`` (startup grace period)."""
    dir_ = dir_ or heartbeat_dir()
    if dir_ is None or not os.path.isdir(dir_):
        return []
    now = time.time()
    up_since = _started_at if _started_at is not None else now
    dead = []
    for r in range(num_workers):
        path = _hb_path(dir_, r)
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            if now - up_since > timeout:
                dead.append(r)
            continue
        if now - mtime > timeout:
            dead.append(r)
    return dead
