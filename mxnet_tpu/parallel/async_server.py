"""Asynchronous parameter server for ``kvstore='dist_async'``.

Reference semantics being reproduced (src/kvstore/kvstore_dist_server.h:348-358
``ApplyUpdates``): in async mode the server applies EVERY worker push to the
global weights immediately — no aggregation barrier, no waiting for the other
workers — and pulls return whatever the weights are right now. Workers
therefore progress at their own pace (Hogwild-style bounded staleness).

TPU-native placement: the reference runs dedicated server *processes*
(ps-lite); here the server is a background THREAD on rank 0 speaking a tiny
length-prefixed-pickle TCP protocol. Rationale: the synchronous fast path
does not need a server at all (GSPMD collectives inside the fused step), so
the async path only has to serve the eager kvstore surface — a host thread
next to rank 0's chip is the lightest faithful topology, and the update math
runs through the same Optimizer/Updater code the local kvstore uses (the
reference pickles the optimizer to the server the same way,
python/mxnet/kvstore.py set_optimizer).

Protocol messages (all pickled tuples): ("init", key, np_value),
("push", key, np_grad), ("pull", key), ("set_optimizer", bytes),
("command", head, body), ("stats",), ("shutdown",).
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading

import numpy as _np

__all__ = ["Server", "Client"]


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


class Server:
    """Rank-0 async parameter server thread."""

    def __init__(self):
        self._store = {}          # key -> np.ndarray (current weights)
        self._updater = None
        self._locks = {}          # per-key: pushes to different keys overlap
        self._glock = threading.Lock()
        self._push_log = []       # (monotonic_ts, key) — test observability
        self._commands = []
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        reply = outer._dispatch(msg)
                        _send_msg(self.request, reply)
                        if msg[0] == "shutdown":
                            return
                except (ConnectionError, OSError):
                    return

        class TS(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        # all interfaces: workers dial the coordinator host's address on
        # multi-host fleets, not loopback
        self._srv = TS(("0.0.0.0", 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="mxnet_tpu-async-server")
        self._thread.start()

    # ------------------------------------------------------------- dispatch
    def _key_lock(self, key):
        with self._glock:
            return self._locks.setdefault(key, threading.Lock())

    def _dispatch(self, msg):
        import time
        op = msg[0]
        if op == "init":
            _, key, value = msg
            with self._key_lock(key):
                # first writer wins (reference server: init is idempotent)
                self._store.setdefault(key, _np.array(value))
            return ("ok",)
        if op == "push":
            _, key, grad = msg
            return self._handle_push(key, grad, time)
        if op == "pushq":
            # 2-bit wire-compressed push: the worker shipped PACKED codes
            # (~16x smaller than f32); dequantize server-side
            from ..kvstore import _dequantize_2bit
            _, key, packed, shape, thr = msg
            return self._handle_push(
                key, _dequantize_2bit(packed, shape, thr), time)
        if op == "pull":
            _, key = msg
            with self._key_lock(key):
                if key not in self._store:
                    return ("err", "key %r not initialized" % key)
                return ("ok", _np.array(self._store[key]))
        if op == "set_optimizer":
            from .. import optimizer as _opt
            optimizer = pickle.loads(msg[1])
            self._updater = _opt.get_updater(optimizer)
            return ("ok",)
        if op == "command":
            # reference kSetOptimizer-style control messages
            # (include/mxnet/kvstore.h:49); recorded and ack'd
            self._commands.append((msg[1], msg[2]))
            return ("ok",)
        if op == "stats":
            return ("ok", {"pushes": list(self._push_log),
                           "commands": list(self._commands)})
        if op == "shutdown":
            threading.Thread(target=self._srv.shutdown,
                             daemon=True).start()
            return ("ok",)
        return ("err", "unknown op %r" % (op,))

    def _handle_push(self, key, grad, time):
        with self._key_lock(key):
            if key not in self._store:
                return ("err", "key %r not initialized" % key)
            if self._updater is None:
                self._store[key] = _np.array(grad)
            else:
                self._apply(key, grad)
        self._push_log.append((time.monotonic(), key))
        return ("ok",)

    def _apply(self, key, grad):
        """Apply one push through the real Updater — identical math to the
        local kvstore path (reference server reuses the optimizer op too)."""
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp
        w = NDArray(jnp.asarray(self._store[key]))
        g = NDArray(jnp.asarray(grad))
        self._updater(_key_int(key), g, w)
        self._store[key] = _np.asarray(w._data)


def _key_int(key):
    """Updaters index per-key optimizer state by int when possible."""
    try:
        return int(key)
    except (TypeError, ValueError):
        return key


class Client:
    """One worker's connection to the async server."""

    def __init__(self, host, port, timeout=60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._lock = threading.Lock()

    def call(self, *msg):
        with self._lock:
            _send_msg(self._sock, msg)
            reply = _recv_msg(self._sock)
        if reply[0] != "ok":
            from ..base import MXNetError
            raise MXNetError("async server: %s" % (reply[1],))
        return reply[1] if len(reply) > 1 else None

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
