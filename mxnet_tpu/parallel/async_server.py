"""Asynchronous parameter server for ``kvstore='dist_async'``.

Reference semantics being reproduced (src/kvstore/kvstore_dist_server.h:348-358
``ApplyUpdates``): in async mode the server applies EVERY worker push to the
global weights immediately — no aggregation barrier, no waiting for the other
workers — and pulls return whatever the weights are right now. Workers
therefore progress at their own pace (Hogwild-style bounded staleness).

TPU-native placement: the reference runs dedicated server *processes*
(ps-lite); here the server is a background THREAD on rank 0. Rationale: the
synchronous fast path does not need a server at all (GSPMD collectives inside
the fused step), so the async path only has to serve the eager kvstore
surface — a host thread next to rank 0's chip is the lightest faithful
topology, and the update math runs through the same Optimizer/Updater code
the local kvstore uses.

Wire protocol + trust model (ps-lite message framing analog,
reference src/kvstore/kvstore_dist.h:44-58; see docs/distributed.md):

* Frame: ``<Q total_len> <32B HMAC-SHA256 tag> <payload>``; payload is
  ``<I header_len> <JSON header> <raw tensor bytes>``. Tensors travel as
  raw little-endian buffers described by header dtype/shape — NO pickle
  on the tensor path.
* Every frame is HMAC-authenticated with a shared secret
  (``MXNET_KVSTORE_SECRET``) and VERIFIED BEFORE ANY PARSING; a bad tag
  drops the connection. Without an explicit secret the server generates
  a process-local one and binds LOOPBACK ONLY, so it is unreachable
  remotely. Binding a non-loopback interface (``MXNET_KVSTORE_BIND`` or
  the coordinator interface on multi-host fleets) requires an explicit
  shared secret — refused loudly otherwise.
* ``set_optimizer`` is the one opaque payload (the reference ships the
  pickled optimizer the same way, python/mxnet/kvstore.py
  set_optimizer); it deserializes only after HMAC verification, so only
  holders of the secret can reach that code path.
* Each client THREAD gets its own connection (thread-local socket), so
  one worker's push and pull overlap instead of serializing through a
  single socket, and a large push does not head-of-line-block control
  messages on another thread.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import os
import pickle
import secrets as _secrets
import socket
import socketserver
import struct
import threading
import weakref

import time as _time

import numpy as _np

from . import faultinject

__all__ = ["Server", "Client"]

_TAG_LEN = 32

# refuse absurd frame-length claims BEFORE buffering the payload — an
# unauthenticated peer controls the length field (tag checks come after
# the read), so the default bounds what such a peer can make us buffer to
# 256 MiB per connection. Tunable for jobs shipping truly huge single
# tensors (a 4 GiB-era default let one pre-auth connection pin ~4 GiB).
_MAX_FRAME = int(os.environ.get("MXNET_KVSTORE_MAX_FRAME", str(256 << 20)))

# process-local default secret: single-process topologies (server thread +
# in-process clients) share it implicitly; separate processes must export
# MXNET_KVSTORE_SECRET (tools/launch.py generates one per job)
_process_secret = _secrets.token_bytes(32)


def _secret():
    """Derived HMAC key. Called once per Server/Client construction —
    not per frame — so env lookup + sha256 stay off the hot path."""
    s = os.environ.get("MXNET_KVSTORE_SECRET")
    if s:
        return hashlib.sha256(s.encode()).digest()
    return _process_secret


def _is_loopback(bind):
    return bind in ("127.0.0.1", "localhost", "::1")


class _Channel:
    """Per-connection anti-replay state: a server-issued random challenge
    plus a monotonic frame counter, both mixed into every frame's HMAC
    input (frame #n MACs ``challenge || n || payload``). The request/reply
    protocol is lock-step, so both ends advance the same counter sequence;
    a frame captured earlier (same connection or any previous one) MACs
    over the wrong (challenge, counter) pair and is rejected exactly like
    a forgery — replays and reordering are dropped, not applied."""

    __slots__ = ("challenge", "n")

    def __init__(self, challenge):
        self.challenge = challenge
        self.n = 0

    def _mac_prefix(self):
        # consumed exactly once per frame, in wire order
        prefix = self.challenge + struct.pack("<Q", self.n)
        self.n += 1
        return prefix


def _send_frame(sock, header, blob=b"", key=None, chan=None):
    hdr = json.dumps(header).encode()
    payload = struct.pack("<I", len(hdr)) + hdr + blob
    prefix = chan._mac_prefix() if chan is not None else b""
    tag = hmac.new(key or _secret(), prefix + payload,
                   hashlib.sha256).digest()
    sock.sendall(struct.pack("<Q", _TAG_LEN + len(payload)) + tag + payload)


def _host_of(addr):
    """Host part of a ``host:port`` coordinator address; tolerates
    bracketed IPv6 (``[::1]:9091`` -> ``::1``)."""
    host = addr.rsplit(":", 1)[0]
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock, key=None, chan=None):
    (total,) = struct.unpack("<Q", _recv_exact(sock, 8))
    if total < _TAG_LEN + 4 or total > _MAX_FRAME:
        raise ConnectionError("malformed frame (claimed %d bytes)" % total)
    tag = _recv_exact(sock, _TAG_LEN)
    payload = _recv_exact(sock, total - _TAG_LEN)
    # authenticate BEFORE parsing anything; the channel prefix makes a
    # replayed/reordered frame fail exactly like a forgery
    prefix = chan._mac_prefix() if chan is not None else b""
    want = hmac.new(key or _secret(), prefix + payload,
                    hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise ConnectionError("frame failed authentication"
                              + (" (stale counter/replay?)"
                                 if chan is not None else ""))
    (hlen,) = struct.unpack("<I", payload[:4])
    header = json.loads(payload[4:4 + hlen].decode())
    return header, payload[4 + hlen:]


def _pack_array(arr):
    arr = _np.ascontiguousarray(arr)
    return ({"dtype": arr.dtype.str, "shape": list(arr.shape)},
            arr.tobytes())


def _unpack_array(meta, blob):
    return _np.frombuffer(blob, dtype=_np.dtype(meta["dtype"])) \
        .reshape(meta["shape"]).copy()


class Server:
    """Rank-0 async parameter server thread.

    ``bind``: interface to listen on. Defaults to ``MXNET_KVSTORE_BIND``,
    else loopback. Non-loopback binds require MXNET_KVSTORE_SECRET."""

    def __init__(self, bind=None):
        bind = bind or os.environ.get("MXNET_KVSTORE_BIND") or "127.0.0.1"
        if not _is_loopback(bind) and \
                not os.environ.get("MXNET_KVSTORE_SECRET"):
            raise RuntimeError(
                "async kvstore server: refusing to bind non-loopback "
                "interface %r without MXNET_KVSTORE_SECRET set — remote "
                "peers must authenticate (see docs/distributed.md)" % bind)
        self._store = {}          # key -> np.ndarray (current weights)
        self._updater = None
        self._locks = {}          # per-key: pushes to different keys overlap
        self._glock = threading.Lock()
        self._push_log = []       # (monotonic_ts, key) — test observability
        self._commands = []
        self._hmac_key = _secret()
        # shutdown drain: handlers poll this between requests, so stopping
        # lets every in-flight push/pull FINISH (and its reply flush)
        # instead of a daemon thread dying mid-_apply with half-updated
        # weights and a worker wedged on a reply that never comes
        self._stop = threading.Event()
        self._active = 0          # connections currently inside handle()
        self._closed = False
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                import select
                with outer._glock:
                    outer._active += 1
                try:
                    # per-connection anti-replay channel: issue a fresh
                    # random challenge in a hello frame (MAC'd with the
                    # shared key alone — the peer can't know the challenge
                    # yet), then every subsequent frame in either direction
                    # MACs over challenge || counter || payload
                    challenge = _secrets.token_bytes(16)
                    _send_frame(self.request,
                                {"op": "hello",
                                 "challenge": challenge.hex()},
                                key=outer._hmac_key)
                    chan = _Channel(challenge)
                    while not outer._stop.is_set():
                        # wait for readability OUTSIDE _recv_frame: a plain
                        # socket timeout could fire mid-frame and desync
                        # the stream; this poll only gates the idle gap
                        # between requests
                        ready, _, _ = select.select([self.request], [], [],
                                                    0.5)
                        if not ready:
                            continue
                        header, blob = _recv_frame(self.request,
                                                   key=outer._hmac_key,
                                                   chan=chan)
                        # injected server-side drop ("conn_drop@serve=OP"):
                        # raised OUTSIDE the dispatch try so it falls
                        # through to the outer handler and severs the
                        # connection exactly like a peer failure
                        faultinject.fire("serve", op=header.get("op"))
                        try:
                            reply_hdr, reply_blob = outer._dispatch(header,
                                                                    blob)
                        except Exception as e:  # authenticated-but-bad
                            # frame (e.g. version skew): protocol error
                            # reply, not a handler traceback + disconnect
                            if os.environ.get("MXNET_ASYNC_DEBUG"):
                                import traceback
                                traceback.print_exc()
                            reply_hdr, reply_blob = {
                                "status": "err",
                                "error": "%s: %s" % (type(e).__name__,
                                                     e)}, b""
                        _send_frame(self.request, reply_hdr, reply_blob,
                                    key=outer._hmac_key, chan=chan)
                        if header.get("op") == "shutdown":
                            return
                except (ConnectionError, OSError, ValueError):
                    return  # incl. failed authentication: drop the peer
                finally:
                    with outer._glock:
                        outer._active -= 1

        class TS(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = TS((bind, 0), Handler)
        self.bind = bind
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="mxnet_tpu-async-server")
        self._thread.start()

    # ------------------------------------------------------------- dispatch
    def _key_lock(self, key):
        with self._glock:
            return self._locks.setdefault(key, threading.Lock())

    def _dispatch(self, header, blob):
        import time
        op = header.get("op")
        key = header.get("key")
        if op == "init":
            with self._key_lock(key):
                # first writer wins (reference server: init is idempotent)
                self._store.setdefault(key, _unpack_array(header, blob))
            return {"status": "ok"}, b""
        if op == "push":
            return self._handle_push(key, _unpack_array(header, blob), time)
        if op == "pushq":
            # 2-bit wire-compressed push: the worker shipped PACKED codes
            # (~16x smaller than f32); dequantize server-side
            from ..kvstore import _dequantize_2bit
            packed = _np.frombuffer(blob, _np.uint8)
            return self._handle_push(
                key, _dequantize_2bit(packed, tuple(header["shape"]),
                                      header["thr"]), time)
        if op == "pull":
            with self._key_lock(key):
                if key not in self._store:
                    return {"status": "err",
                            "error": "key %r not initialized" % key}, b""
                meta, raw = _pack_array(self._store[key])
                meta["status"] = "ok"
                return meta, raw
        if op == "set_optimizer":
            from .. import optimizer as _opt
            # opaque payload — reached only through an authenticated frame
            optimizer = pickle.loads(blob)
            self._updater = _opt.get_updater(optimizer)
            return {"status": "ok"}, b""
        if op == "command":
            # reference kSetOptimizer-style control messages
            # (include/mxnet/kvstore.h:49); recorded and ack'd
            self._commands.append((header["head"], header["body"]))
            return {"status": "ok"}, b""
        if op == "stats":
            return {"status": "ok",
                    "stats": {"pushes": list(self._push_log),
                              "commands": [list(c) for c in
                                           self._commands]}}, b""
        if op == "shutdown":
            # the requesting handler still has its "ok" reply to flush, so
            # the full drain runs on a side thread; close() waits for the
            # active-handler census (this connection included) to hit zero
            threading.Thread(target=self.close, daemon=True).start()
            return {"status": "ok"}, b""
        return {"status": "err", "error": "unknown op %r" % (op,)}, b""

    def close(self, drain_s=5.0):
        """Stop accepting work and shut the listener down after a BOUNDED
        drain: handlers finish (at most) their current request — replies
        flushed, no weight left half-applied — then exit at the next
        stop-event poll. Idempotent; safe from any thread."""
        with self._glock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        deadline = _time.monotonic() + max(drain_s, 0.0)
        while _time.monotonic() < deadline:
            with self._glock:
                if self._active == 0:
                    break
            _time.sleep(0.05)
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=max(drain_s, 1.0))

    def _handle_push(self, key, grad, time):
        with self._key_lock(key):
            if key not in self._store:
                return {"status": "err",
                        "error": "key %r not initialized" % key}, b""
            if self._updater is None:
                self._store[key] = _np.array(grad)
            else:
                self._apply(key, grad)
        self._push_log.append((time.monotonic(), key))
        return {"status": "ok"}, b""

    def _apply(self, key, grad):
        """Apply one push through the real Updater — identical math to the
        local kvstore path (reference server reuses the optimizer op too)."""
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp
        w = NDArray(jnp.asarray(self._store[key]))
        g = NDArray(jnp.asarray(grad))
        self._updater(_key_int(key), g, w)
        self._store[key] = _np.asarray(w._data)


def _key_int(key):
    """Updaters index per-key optimizer state by int when possible."""
    try:
        return int(key)
    except (TypeError, ValueError):
        return key


class Client:
    """One worker's connection pool to the async server.

    Connections are per-thread (thread-local), so calls from different
    threads — e.g. a trainer pushing while a prefetcher pulls — overlap
    on independent sockets instead of serializing behind one lock."""

    def __init__(self, host, port, timeout=60.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._hmac_key = _secret()
        self._tls = threading.local()
        self._conns = []          # weakrefs: threads own their sockets
        self._conns_lock = threading.Lock()
        self._closed = threading.Event()
        self._connect()  # fail fast on a bad address

    def _connect(self):
        if self._closed.is_set():
            # a racing call() in another thread must not resurrect a
            # connection after close() — it would hang on a server that
            # is itself draining
            raise ConnectionError("async kvstore client is closed")
        sock = getattr(self._tls, "sock", None)
        if sock is None:
            sock = socket.create_connection(self._addr,
                                            timeout=self._timeout)
            # the server opens every connection with a hello frame carrying
            # the anti-replay challenge; all later frames MAC over it plus
            # the lock-step frame counter
            hello, _ = _recv_frame(sock, key=self._hmac_key)
            if hello.get("op") != "hello" or "challenge" not in hello:
                _close_quietly(sock)
                raise ConnectionError(
                    "async server handshake: expected hello frame, got %r"
                    % (hello.get("op"),))
            self._tls.chan = _Channel(bytes.fromhex(hello["challenge"]))
            self._tls.sock = sock
            with self._conns_lock:
                self._conns = [r for r in self._conns if r() is not None]
                self._conns.append(weakref.ref(sock))
            # close promptly when the owning thread dies (its Thread
            # object is collected), not at interpreter exit — otherwise
            # short-lived kvstore-touching threads leak fds + matching
            # server handler threads
            weakref.finalize(threading.current_thread(), _close_quietly,
                             sock)
        return sock, self._tls.chan

    # ops safe to retry after a connection failure: init is idempotent
    # server-side (first writer wins), pull/stats are pure reads. A push
    # is NOT — the server may have applied the update before the reply
    # was lost, and re-pushing would apply the gradient twice.
    _IDEMPOTENT = frozenset(("init", "pull", "stats"))

    def call(self, op, *args):
        header = {"op": op}
        blob = b""
        if op in ("init", "push"):
            key, value = args
            meta, blob = _pack_array(value)
            header.update(meta, key=key)
        elif op == "pushq":
            key, packed, shape, thr = args
            header.update(key=key, shape=list(shape), thr=float(thr))
            blob = _np.ascontiguousarray(packed, _np.uint8).tobytes()
        elif op == "pull":
            header["key"] = args[0]
        elif op == "set_optimizer":
            blob = args[0]
        elif op == "command":
            header.update(head=args[0], body=args[1])
        elif op in ("stats", "shutdown"):
            pass
        else:
            raise ValueError("unknown kvstore op %r" % op)

        retries = int(os.environ.get("MXNET_KVSTORE_RETRIES", "3")) \
            if op in self._IDEMPOTENT else 0
        backoff = float(os.environ.get("MXNET_KVSTORE_RETRY_BACKOFF",
                                       "0.05"))
        attempt = 0
        while True:
            sock, chan = self._connect()
            try:
                # injected client-side drop ("conn_drop@call=OP") lands
                # here so the cleanup + retry path below handles it like
                # a real mid-call connection loss
                faultinject.fire("call", op=op)
                _send_frame(sock, header, blob, key=self._hmac_key,
                            chan=chan)
                reply, rblob = _recv_frame(sock, key=self._hmac_key,
                                           chan=chan)
                break
            except OSError as e:
                # timeout / ConnectionError: the request-reply stream (and
                # the channel counter) is desynced — drop the thread-local
                # socket so the next attempt reconnects cleanly (fresh
                # hello challenge) instead of reusing it
                self._tls.sock = None
                self._tls.chan = None
                _close_quietly(sock)
                if attempt < retries and not self._closed.is_set():
                    attempt += 1
                    _time.sleep(min(2.0, backoff * (2 ** (attempt - 1))))
                    continue
                if op in ("push", "pushq"):
                    # fail fast, naming who died: a lost push may already
                    # be applied server-side, so retrying is unsound — the
                    # caller must treat this as fatal and resume from a
                    # checkpoint instead
                    from ..base import MXNetError
                    from . import fault
                    nw = int(os.environ.get("MXNET_NUM_WORKERS", "1"))
                    dead = fault.dead_nodes(nw, timeout=_dead_timeout())
                    raise MXNetError(
                        "async kvstore: connection lost during %r (%s); "
                        "push is not retried (may already be applied "
                        "server-side). dead node(s): %s"
                        % (op, e, dead if dead else "none detected yet"))
                raise
        if reply.get("status") != "ok":
            from ..base import MXNetError
            raise MXNetError("async server: %s" % reply.get("error"))
        if "dtype" in reply:
            return _unpack_array(reply, rblob)
        if "stats" in reply:
            # JSON carries tuples as lists; restore the documented shape
            st = reply["stats"]
            st["pushes"] = [tuple(p) for p in st.get("pushes", [])]
            st["commands"] = [tuple(c) for c in st.get("commands", [])]
            return st
        return None

    def close(self):
        self._closed.set()   # before the socket sweep: no reconnect race
        with self._conns_lock:
            refs, self._conns = self._conns, []
        for ref in refs:
            sock = ref()
            if sock is not None:
                _close_quietly(sock)


def _dead_timeout():
    try:
        return float(os.environ.get("MXNET_HEARTBEAT_TIMEOUT", "60"))
    except ValueError:
        return 60.0


def _close_quietly(sock):
    try:
        sock.close()
    except OSError:
        pass
