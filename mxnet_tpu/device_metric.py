"""Device-resident metric accumulation for the fused train step.

The reference fit loop pays one device->host sync per batch to update
``EvalMetric`` (metric.py ``asnumpy``); behind a remote TPU that transfer
dominates the step. Here the accumulation for the common classification
metrics (acc / top_k / ce / nll / loss) is folded INTO the jitted fused
step: a tiny ``(sum f32, count i32)`` carry per metric rides the donated
opt-state, and values move to host only when someone actually reads them
(``Speedometer`` display, epoch-end logging) — one small ``device_get``
of the whole carry per read, not one per batch.

The host ``EvalMetric`` object stays the single source of truth for
presentation: publish overwrites its ``sum_metric``/``num_inst`` and its
own ``get()`` formats the value, so ``Perplexity.get``-style post-
processing and callback code that pokes the metric keep working.

Semantics note: device sums accumulate in f32 in the compiled program;
the host path accumulates in python float64. Counts (acc/top_k) are
integer-valued either way; CE/loss sums agree to f32 rounding. What IS
bitwise-stable is the device path against itself: the same program
sequence at any engine depth or steps_per_dispatch produces identical
bits, which tests/test_async_loop.py and tests/test_step_sync_budget.py
assert.
"""
from __future__ import annotations

import numpy as _np

from . import metric as _metric

__all__ = ["plan_for", "DeviceMetricPlan", "DeviceMetricProxy"]


def _leaves(metric):
    """Flatten a (possibly composite) metric into leaf EvalMetrics, or
    None if any level is unsupported for device accumulation."""
    if isinstance(metric, _metric.CompositeEvalMetric):
        out = []
        for m in metric.metrics:
            sub = _leaves(m)
            if sub is None:
                return None
            out.extend(sub)
        return out
    return [metric]


def _select_names(m, out_names, label_names):
    """Replicate EvalMetric.update_dict's name selection statically."""
    if m.output_names is not None:
        preds = [n for n in m.output_names if n in out_names]
    else:
        preds = list(out_names)
    if m.label_names is not None:
        labels = [n for n in m.label_names if n in label_names]
    else:
        labels = list(label_names)
    return labels, preds


def _build_update(m, label_keys, pred_keys):
    """Return a pure jnp update ``(sum, count, labels, preds) ->
    (sum, count)`` replicating ``m.update``'s math, or None if ``m`` is
    not device-fusable (stateful F1/MCC, per-batch-mean regression
    metrics, arbitrary CustomMetric fevals)."""
    import jax.numpy as jnp

    f32, i32 = jnp.float32, jnp.int32
    # exact class checks (not isinstance): a subclass may override update
    # with math the closure below would silently misrepresent.
    # NegativeLogLikelihood is the one subclass that changes no math.
    klass = type(m)

    if klass is _metric.Accuracy:
        axis = m.axis

        def upd(s, n, labels, preds):
            for label, pred in zip(labels, preds):
                if pred.ndim > label.ndim:
                    pred = jnp.argmax(pred, axis=axis)
                pred = pred.astype(i32).ravel()
                label = label.astype(i32).ravel()
                s = s + jnp.sum(pred == label).astype(f32)
                n = n + i32(label.size)
            return s, n
        return upd

    if klass is _metric.TopKAccuracy:
        top_k = m.top_k

        def upd(s, n, labels, preds):
            for label, pred in zip(labels, preds):
                label = label.astype(i32)
                idx = jnp.argsort(pred, axis=1)[:, -top_k:]
                hit = (idx == label.reshape(-1, 1)).any(axis=1)
                s = s + jnp.sum(hit).astype(f32)
                n = n + i32(label.shape[0])
            return s, n
        return upd

    if klass in (_metric.CrossEntropy, _metric.NegativeLogLikelihood):
        eps = m.eps

        def upd(s, n, labels, preds):
            for label, pred in zip(labels, preds):
                label = label.ravel().astype(i32)
                pred = pred.astype(f32)
                prob = pred[jnp.arange(label.shape[0]), label]
                s = s + jnp.sum(-jnp.log(prob + eps))
                n = n + i32(label.shape[0])
            return s, n
        return upd

    if klass in (_metric.Loss, _metric.Torch, _metric.Caffe):
        def upd(s, n, labels, preds):
            for pred in preds:
                s = s + jnp.sum(pred).astype(f32)
                n = n + i32(pred.size)
            return s, n
        return upd

    return None


def plan_for(metric, out_names, label_names):
    """Build a :class:`DeviceMetricPlan` for ``metric`` over a module
    with the given output/label names, or None when any leaf metric's
    math cannot be replicated on device (caller falls back to the
    per-batch host path)."""
    leaves = _leaves(metric)
    if leaves is None or not leaves:
        return None
    entries = []
    for m in leaves:
        lab_keys, pred_keys = _select_names(m, out_names, label_names)
        if not pred_keys:
            return None
        needs_labels = not isinstance(m, _metric.Loss)
        if needs_labels and len(lab_keys) != len(pred_keys):
            # host update would zip-truncate or _check-raise; don't guess
            return None
        upd = _build_update(m, lab_keys, pred_keys)
        if upd is None:
            return None
        entries.append((m, lab_keys, pred_keys, upd))
    return DeviceMetricPlan(entries)


class DeviceMetricPlan:
    """Compiled-side metric accumulation: ``update`` is traced inside the
    fused step; ``init_state``/``publish`` bracket it on the host."""

    def __init__(self, entries):
        self._entries = entries

    @property
    def leaves(self):
        return [e[0] for e in self._entries]

    def init_state(self):
        """Fresh zero carry: one (sum f32, count i32) pair per leaf."""
        return tuple((_np.float32(0.0), _np.int32(0))
                     for _ in self._entries)

    def update(self, state, label_dict, pred_dict):
        """Pure traced update: new state from one step's outputs/labels.
        Runs INSIDE the jitted fused step (and its lax.scan body)."""
        new = []
        for (m, lab_keys, pred_keys, upd), (s, n) in zip(self._entries,
                                                         state):
            labels = [label_dict[k] for k in lab_keys if k in label_dict]
            preds = [pred_dict[k] for k in pred_keys if k in pred_dict]
            new.append(upd(s, n, labels, preds))
        return tuple(new)

    def publish(self, host_state):
        """Overwrite each leaf metric's host accumulators from a fetched
        carry (caller did the single device_get)."""
        for (m, _, _, _), (s, n) in zip(self._entries, host_state):
            m.sum_metric = float(s)
            m.num_inst = int(n)


class DeviceMetricProxy:
    """Quacks like the wrapped EvalMetric for fit's loop and callbacks,
    but the accumulation lives on device: reads (``get`` /
    ``get_name_value``) publish the device carry into the wrapped metric
    first; ``update``/``update_dict`` are no-ops (the fused step already
    accumulated this batch); ``reset`` zeros both sides."""

    _device_resident = True

    def __init__(self, module, inner):
        self._module = module
        self.inner = inner
        self._pub_version = -1

    @property
    def name(self):
        return self.inner.name

    @property
    def sum_metric(self):
        self._publish()
        return self.inner.sum_metric

    @property
    def num_inst(self):
        self._publish()
        return self.inner.num_inst

    def _publish(self):
        mod = self._module
        version = getattr(mod, "_device_met_version", 0)
        if version != self._pub_version:
            mod._publish_device_metric()
            self._pub_version = version

    def update(self, labels, preds):
        pass  # accumulated inside the fused step

    def update_dict(self, label, pred):
        pass  # accumulated inside the fused step

    def reset(self):
        self._module._reset_device_metric()
        self.inner.reset()
        self._pub_version = getattr(self._module, "_device_met_version", 0)

    def get(self):
        self._publish()
        return self.inner.get()

    def get_name_value(self):
        self._publish()
        return self.inner.get_name_value()

    def __str__(self):
        return "DeviceMetricProxy(%s)" % self.inner
