"""Shape-keyed auto-tuning for the Pallas kernel tier.

Offline search (``tuner.tune`` via ``tools/autotune.py``), chip-free
ranking (``cost_model``), and the versioned winners file the dispatch
layer consults at trace time (``cache``). See docs/tuning.md.
"""
from . import cache    # noqa: F401  (import-light; no jax)
from . import space    # noqa: F401
from .cache import (TuningCache, CacheRewriteError,  # noqa: F401
                    shape_bucket_key, lookup_config, get_default,
                    invalidate_default, SCHEMA_VERSION)

__all__ = ["cache", "space", "TuningCache", "CacheRewriteError",
           "shape_bucket_key", "lookup_config", "get_default",
           "invalidate_default", "SCHEMA_VERSION"]
