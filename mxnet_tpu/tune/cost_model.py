"""Chip-free learned cost model for ranking kernel configs.

The Learned-Performance-Model-for-TPUs result (PAPERS.md, 2008.01040) is
that tile winners are predictable from *static* features — no chip in
the loop. This model is the smallest honest version of that: per config
we extract a feature vector (HBM roofline terms from bytes-moved and
FLOPs via the shared ``mxnet_tpu.perfmodel`` tables, grid size, VMEM
footprint, tile-alignment and padding-waste penalties) and score it with
a linear model. The default weights were fit offline with
:meth:`LinearCostModel.fit` (ordinary least squares) against
interpreter-calibrated microbench timings and then rounded; when a chip
IS available the tuner measures instead and can re-fit, so the model
only ever has to *rank* correctly, not predict absolute microseconds.

Everything here is deterministic: same inputs -> same features -> same
scores -> same ranking (an acceptance criterion).
"""
from __future__ import annotations

from ..perfmodel import peak_flops, hbm_bytes_per_s, DEFAULT_DEVICE_KIND
from .space import VMEM_BYTES

__all__ = ["FEATURE_NAMES", "features", "LinearCostModel",
           "default_model", "save_weights", "default_weights_path"]

FEATURE_NAMES = ("hbm_time_us", "flop_time_us", "grid_overhead_us",
                 "misalign", "waste", "vmem_frac")


def _dtype_bytes(dtype):
    d = str(dtype)
    if "bfloat16" in d or "float16" in d:
        return 2
    if "8" in d:
        return 1
    return 4


def _pad(n, block):
    return ((n + block - 1) // block) * block


def features(op, shapes, dtype, config,
             device_kind=DEFAULT_DEVICE_KIND):
    """Static feature dict for one (op, shapes, dtype, config) point."""
    b = _dtype_bytes(dtype)
    if op == "bn_act":
        (R, S), = shapes[:1]
        br, bs = config["block_r"], config["block_s"]
        Rp, Sp = _pad(R, br), _pad(S, bs)
        elems = Rp * Sp
        hbm_bytes = 3 * elems * b + 2 * Rp * 4     # x in, res in, out, coefs
        flops = 4.0 * elems                        # mul+add+add+max, f32
        grid = (Rp // br) * (Sp // bs)
        vmem = 3 * br * bs * b + 2 * br * 4 + br * bs * 4
        misalign = (br % 8 != 0) + (bs % 128 != 0)
        waste = elems / float(max(R * S, 1)) - 1.0
    elif op == "scale_bias_act":
        (R, F), = shapes[:1]
        br, bf = config["block_r"], config["block_f"]
        Rp, Fp = _pad(R, br), _pad(F, bf)
        elems = Rp * Fp
        hbm_bytes = 2 * elems * b + 2 * Fp * 4
        flops = 12.0 * elems                       # erf-gelu polynomial
        grid = (Rp // br) * (Fp // bf)
        vmem = 2 * br * bf * b + 2 * bf * 4 + br * bf * 4
        misalign = (br % 8 != 0) + (bf % 128 != 0)
        waste = elems / float(max(R * F, 1)) - 1.0
    elif op == "take_rows":
        (V, D) = shapes[0]
        (L,) = shapes[1]
        bd = config["block_d"]
        Dp = _pad(D, bd)
        hbm_bytes = 2 * L * Dp * b + L * 4
        flops = 0.0
        grid = L * (Dp // bd)
        vmem = 2 * bd * b
        misalign = 1 if bd % 128 != 0 else 0
        waste = Dp / float(max(D, 1)) - 1.0
    else:
        raise KeyError("no cost features for op %r" % (op,))
    return {
        "hbm_time_us": 1e6 * hbm_bytes / hbm_bytes_per_s(device_kind),
        "flop_time_us": 1e6 * flops / peak_flops(device_kind),
        "grid_overhead_us": 1e-1 * grid,   # ~0.1us grid-step bookkeeping
        "misalign": float(misalign),
        "waste": max(0.0, waste),
        "vmem_frac": vmem / float(VMEM_BYTES),
    }


class LinearCostModel:
    """score(config) = w . features  (predicted microseconds-ish)."""

    def __init__(self, weights=None):
        self.weights = dict(self.DEFAULT_WEIGHTS)
        if weights:
            self.weights.update(weights)

    # offline-fit against interpreter-calibrated microbench rankings,
    # rounded to one significant digit: the roofline terms dominate,
    # misaligned tiles cost ~a roofline's worth, padding waste and
    # near-VMEM-limit blocks are discouraged, tiny grids (no pipeline
    # overlap) pay per-step overhead
    DEFAULT_WEIGHTS = {
        "hbm_time_us": 1.0,
        "flop_time_us": 1.0,
        "grid_overhead_us": 1.0,
        "misalign": 50.0,
        "waste": 30.0,
        "vmem_frac": 5.0,
    }

    def predict(self, feat):
        return sum(self.weights[k] * feat[k] for k in FEATURE_NAMES)

    def score(self, op, shapes, dtype, config,
              device_kind=DEFAULT_DEVICE_KIND):
        return self.predict(features(op, shapes, dtype, config,
                                     device_kind))

    def fit(self, feature_rows, times_us):
        """Ordinary least squares over measured times -> a new model.
        Used when on-chip measurements exist to recalibrate the
        chip-free ranking; returns self with updated weights."""
        import numpy as np
        X = np.array([[row[k] for k in FEATURE_NAMES]
                      for row in feature_rows], dtype=np.float64)
        y = np.asarray(times_us, dtype=np.float64)
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        self.weights = dict(zip(FEATURE_NAMES, (float(v) for v in w)))
        return self

    def to_dict(self):
        return dict(self.weights)


WEIGHTS_VERSION = 1
_loaded_weights = (None, None, None)   # (path, mtime, weights | None)


def default_weights_path():
    """Recalibrated-weights file consulted by :func:`default_model`:
    ``MXNET_KERNEL_COST_MODEL`` when set, else unset (ship weights)."""
    try:
        from mxnet_tpu.config import flags
        return flags.kernel_cost_model or None
    except Exception:
        return None


def save_weights(model, path):
    """Persist recalibrated weights (``autotune.py --recalibrate
    --save-model``) in the format ``default_model`` reloads."""
    import json
    import os
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": WEIGHTS_VERSION,
                   "features": list(FEATURE_NAMES),
                   "weights": model.to_dict()}, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _load_weights(path):
    """mtime-memoized read of a persisted weights file; None when the
    file is missing, stale-formatted, or unreadable (ship weights win)."""
    global _loaded_weights
    import json
    import os
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    if _loaded_weights[0] == path and _loaded_weights[1] == mtime:
        return _loaded_weights[2]
    weights = None
    try:
        with open(path) as f:
            doc = json.load(f)
        if (isinstance(doc, dict) and doc.get("version") == WEIGHTS_VERSION
                and isinstance(doc.get("weights"), dict)
                and all(k in doc["weights"] for k in FEATURE_NAMES)):
            weights = {k: float(doc["weights"][k]) for k in FEATURE_NAMES}
    except (OSError, ValueError, TypeError):
        weights = None
    _loaded_weights = (path, mtime, weights)
    return weights


def default_model():
    path = default_weights_path()
    if path:
        weights = _load_weights(path)
        if weights:
            return LinearCostModel(weights)
    return LinearCostModel()
