"""Chip-free learned cost model for ranking kernel configs.

The Learned-Performance-Model-for-TPUs result (PAPERS.md, 2008.01040) is
that tile winners are predictable from *static* features — no chip in
the loop. This model is the smallest honest version of that: per config
we extract a feature vector (HBM roofline terms from bytes-moved and
FLOPs via the shared ``mxnet_tpu.perfmodel`` tables, grid size, VMEM
footprint, tile-alignment and padding-waste penalties) and score it with
a linear model. The default weights were fit offline with
:meth:`LinearCostModel.fit` (ordinary least squares) against
interpreter-calibrated microbench timings and then rounded; when a chip
IS available the tuner measures instead and can re-fit, so the model
only ever has to *rank* correctly, not predict absolute microseconds.

Everything here is deterministic: same inputs -> same features -> same
scores -> same ranking (an acceptance criterion).
"""
from __future__ import annotations

from ..perfmodel import peak_flops, hbm_bytes_per_s, DEFAULT_DEVICE_KIND
from .space import VMEM_BYTES

__all__ = ["FEATURE_NAMES", "features", "LinearCostModel",
           "default_model", "save_weights", "default_weights_path"]

FEATURE_NAMES = ("hbm_time_us", "flop_time_us", "grid_overhead_us",
                 "misalign", "waste", "vmem_frac",
                 # fusion-structure features (attention family): static
                 # bytes/flops cannot separate two tilings of the SAME
                 # computation, so these capture what the fusion actually
                 # changes — elementwise online-softmax work off the MXU,
                 # DMA issue count, and lane/sublane tile padding. Exactly
                 # 0.0 for the pre-existing elementwise/gather ops, so
                 # their committed rankings (and the reproduction test
                 # over tools/kernel_tuning.json) are untouched.
                 "vpu_time_us", "dma_steps", "tile_waste")


def _dtype_bytes(dtype):
    d = str(dtype)
    if "bfloat16" in d or "float16" in d:
        return 2
    if "8" in d:
        return 1
    return 4


def _pad(n, block):
    return ((n + block - 1) // block) * block


def features(op, shapes, dtype, config,
             device_kind=DEFAULT_DEVICE_KIND):
    """Static feature dict for one (op, shapes, dtype, config) point."""
    b = _dtype_bytes(dtype)
    if op == "bn_act":
        (R, S), = shapes[:1]
        br, bs = config["block_r"], config["block_s"]
        Rp, Sp = _pad(R, br), _pad(S, bs)
        elems = Rp * Sp
        hbm_bytes = 3 * elems * b + 2 * Rp * 4     # x in, res in, out, coefs
        flops = 4.0 * elems                        # mul+add+add+max, f32
        grid = (Rp // br) * (Sp // bs)
        vmem = 3 * br * bs * b + 2 * br * 4 + br * bs * 4
        misalign = (br % 8 != 0) + (bs % 128 != 0)
        waste = elems / float(max(R * S, 1)) - 1.0
    elif op == "scale_bias_act":
        (R, F), = shapes[:1]
        br, bf = config["block_r"], config["block_f"]
        Rp, Fp = _pad(R, br), _pad(F, bf)
        elems = Rp * Fp
        hbm_bytes = 2 * elems * b + 2 * Fp * 4
        flops = 12.0 * elems                       # erf-gelu polynomial
        grid = (Rp // br) * (Fp // bf)
        vmem = 2 * br * bf * b + 2 * bf * 4 + br * bf * 4
        misalign = (br % 8 != 0) + (bf % 128 != 0)
        waste = elems / float(max(R * F, 1)) - 1.0
    elif op == "take_rows":
        (V, D) = shapes[0]
        (L,) = shapes[1]
        bd = config["block_d"]
        Dp = _pad(D, bd)
        hbm_bytes = 2 * L * Dp * b + L * 4
        flops = 0.0
        grid = L * (Dp // bd)
        vmem = 2 * bd * b
        misalign = 1 if bd % 128 != 0 else 0
        waste = Dp / float(max(D, 1)) - 1.0
    elif op == "flash_attn":
        (BH, Tq, D), (_BH2, Tk, _D2) = shapes[:2]
        bq, bk = config["block_q"], config["block_k"]
        Tqp, Tkp = _pad(Tq, bq), _pad(Tk, bk)
        n_q, n_k = Tqp // bq, Tkp // bk
        # KV tiles are re-streamed once per q block (the flash trade:
        # no (T, T) score tensor in HBM, more KV reads)
        hbm_bytes = BH * (2 * Tqp * D + 2 * n_q * Tkp * D) * b
        score_elems = float(BH) * Tqp * Tkp
        flops = 4.0 * score_elems * D              # QK^T + PV on the MXU
        vpu_ops = 12.0 * score_elems               # exp/max/sum/correct
        grid = BH * n_q * n_k
        dma = 2.0 * grid                           # one k + one v tile/step
        vmem = (2 * bq * D + 2 * bk * D) * b \
            + (2 * bq + bq * D) * 4 + bq * bk * 4
        misalign = (bq % 8 != 0) + (bk % 128 != 0)
        waste = score_elems / float(max(Tq * Tk * BH, 1)) - 1.0
        tile_w = (_pad(bk, 128) / float(bk) - 1.0) \
            + (_pad(bq, 8) / float(bq) - 1.0) \
            + (_pad(D, 128) / float(D) - 1.0)
    elif op == "flash_attn_paged":
        (S, W, H, Dh), (MP, page) = shapes[:2]
        bh = config["block_h"]
        lanes = bh * Dh
        heads_grid = max(1, H // max(bh, 1))
        grid = S * heads_grid * MP
        ctx = MP * page
        # q/out DMA'd once per (slot, head-block); k/v pages every step.
        # Total page bytes are bh-invariant — the knob moves DMA count
        # and lane fill, which is exactly what the new features carry.
        hbm_bytes = (2 * S * heads_grid * W * lanes
                     + 2 * grid * page * lanes) * b
        score_elems = float(S) * W * H * ctx
        flops = 4.0 * score_elems * Dh
        vpu_ops = 12.0 * score_elems
        dma = 2.0 * grid
        vmem = (2 * W * lanes + 2 * page * lanes) * b \
            + (2 * W * bh + W * lanes) * 4
        misalign = (lanes % 128 != 0) + (page % 8 != 0)
        waste = 0.0
        tile_w = (_pad(lanes, 128) / float(lanes) - 1.0) \
            + (_pad(W, 8) / float(W) - 1.0)
    else:
        raise KeyError("no cost features for op %r" % (op,))
    if op not in ("flash_attn", "flash_attn_paged"):
        vpu_ops, dma, tile_w = 0.0, 0.0, 0.0
    return {
        "hbm_time_us": 1e6 * hbm_bytes / hbm_bytes_per_s(device_kind),
        "flop_time_us": 1e6 * flops / peak_flops(device_kind),
        "grid_overhead_us": 1e-1 * grid,   # ~0.1us grid-step bookkeeping
        "misalign": float(misalign),
        "waste": max(0.0, waste),
        "vmem_frac": vmem / float(VMEM_BYTES),
        # VPU throughput ~ an eighth of the MXU peak: elementwise
        # online-softmax work that bytes/flops features cannot see
        "vpu_time_us": 1e6 * vpu_ops / (peak_flops(device_kind) / 8.0),
        "dma_steps": float(dma),
        "tile_waste": max(0.0, float(tile_w)),
    }


class LinearCostModel:
    """score(config) = w . features  (predicted microseconds-ish)."""

    def __init__(self, weights=None):
        self.weights = dict(self.DEFAULT_WEIGHTS)
        if weights:
            self.weights.update(weights)

    # offline-fit against interpreter-calibrated microbench rankings,
    # rounded to one significant digit: the roofline terms dominate,
    # misaligned tiles cost ~a roofline's worth, padding waste and
    # near-VMEM-limit blocks are discouraged, tiny grids (no pipeline
    # overlap) pay per-step overhead
    DEFAULT_WEIGHTS = {
        "hbm_time_us": 1.0,
        "flop_time_us": 1.0,
        "grid_overhead_us": 1.0,
        "misalign": 50.0,
        "waste": 30.0,
        "vmem_frac": 5.0,
        # fusion-structure terms (0-valued features for the older ops,
        # so their scores are bit-identical to the 6-feature model)
        "vpu_time_us": 1.0,
        "dma_steps": 0.02,     # ~20ns DMA issue cost per tile
        "tile_waste": 10.0,
    }

    def predict(self, feat):
        return sum(self.weights[k] * feat[k] for k in FEATURE_NAMES)

    def score(self, op, shapes, dtype, config,
              device_kind=DEFAULT_DEVICE_KIND):
        return self.predict(features(op, shapes, dtype, config,
                                     device_kind))

    def fit(self, feature_rows, times_us):
        """Ordinary least squares over measured times -> a new model.
        Used when on-chip measurements exist to recalibrate the
        chip-free ranking; returns self with updated weights."""
        import numpy as np
        X = np.array([[row[k] for k in FEATURE_NAMES]
                      for row in feature_rows], dtype=np.float64)
        y = np.asarray(times_us, dtype=np.float64)
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        self.weights = dict(zip(FEATURE_NAMES, (float(v) for v in w)))
        return self

    def to_dict(self):
        return dict(self.weights)


# v2: FEATURE_NAMES grew the fusion-structure triple (vpu_time_us,
# dma_steps, tile_waste); v1 weight files lack those columns and are
# cleanly rejected by _load_weights (ship weights win)
WEIGHTS_VERSION = 2
_loaded_weights = (None, None, None)   # (path, mtime, weights | None)


def default_weights_path():
    """Recalibrated-weights file consulted by :func:`default_model`:
    ``MXNET_KERNEL_COST_MODEL`` when set, else unset (ship weights)."""
    try:
        from mxnet_tpu.config import flags
        return flags.kernel_cost_model or None
    except Exception:
        return None


def save_weights(model, path):
    """Persist recalibrated weights (``autotune.py --recalibrate
    --save-model``) in the format ``default_model`` reloads."""
    import json
    import os
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": WEIGHTS_VERSION,
                   "features": list(FEATURE_NAMES),
                   "weights": model.to_dict()}, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _load_weights(path):
    """mtime-memoized read of a persisted weights file; None when the
    file is missing, stale-formatted, or unreadable (ship weights win)."""
    global _loaded_weights
    import json
    import os
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    if _loaded_weights[0] == path and _loaded_weights[1] == mtime:
        return _loaded_weights[2]
    weights = None
    try:
        with open(path) as f:
            doc = json.load(f)
        if (isinstance(doc, dict) and doc.get("version") == WEIGHTS_VERSION
                and isinstance(doc.get("weights"), dict)
                and all(k in doc["weights"] for k in FEATURE_NAMES)):
            weights = {k: float(doc["weights"][k]) for k in FEATURE_NAMES}
    except (OSError, ValueError, TypeError):
        weights = None
    _loaded_weights = (path, mtime, weights)
    return weights


def default_model():
    path = default_weights_path()
    if path:
        weights = _load_weights(path)
        if weights:
            return LinearCostModel(weights)
    return LinearCostModel()
