"""Bounded per-op config spaces for the kernel tuner (the TVM idea at
TPU scale: a SMALL searchable schedule space per op beats one fixed
kernel, and on TPU the only knobs that matter are tile/block shapes —
layout and vectorization belong to Mosaic).

Spaces are deterministic lists of plain dicts, filtered by hard VMEM
feasibility so the measuring path never launches a config Mosaic would
reject. ``default_config`` is the heuristic the dispatch layer uses when
the tuning cache has no entry ('auto' tier).
"""
from __future__ import annotations

__all__ = ["space_for", "default_config", "VMEM_BYTES"]

# per-core VMEM budget the tuner plans against (half of the 16 MiB v5e
# arsenal: Mosaic needs headroom for double-buffered DMA)
VMEM_BYTES = 8 * 1024 * 1024

_BLOCK_R = (8, 16, 32, 64, 128, 256, 512)
_BLOCK_S = (128, 256, 512, 1024, 2048)
_BLOCK_D = (128, 256, 512, 1024)


def _dtype_bytes(dtype):
    d = str(dtype)
    if "bfloat16" in d or "float16" in d:
        return 2
    if "8" in d:
        return 1
    return 4


def _clamp_pow2ish(options, limit):
    """Options no bigger than the first option >= limit (so tiny dims
    still get one covering block instead of an empty space)."""
    out = [o for o in options if o <= limit]
    bigger = [o for o in options if o > limit]
    if bigger:
        out.append(bigger[0])
    return out or [options[0]]


def space_for(op, shapes, dtype):
    """Deterministic list of candidate configs for (op, shapes, dtype).

    ``shapes`` is the tuple-of-shape-tuples the kernel's
    ``shape_key_shapes`` produced (the kernel's own canonical view).
    """
    b = _dtype_bytes(dtype)
    out = []
    if op == "bn_act":
        (R, S), = shapes[:1]
        for br in _clamp_pow2ish(_BLOCK_R, R):
            for bs in _clamp_pow2ish(_BLOCK_S, S):
                # x block + residual/out blocks (in+out+res) + coef column
                vmem = 3 * br * bs * b + 2 * br * 4 + br * bs * 4
                if vmem <= VMEM_BYTES:
                    out.append({"block_r": br, "block_s": bs})
    elif op == "scale_bias_act":
        (R, F), = shapes[:1]
        for br in _clamp_pow2ish(_BLOCK_R, R):
            for bf in _clamp_pow2ish(_BLOCK_S, F):
                vmem = 2 * br * bf * b + 2 * bf * 4 + br * bf * 4
                if vmem <= VMEM_BYTES:
                    out.append({"block_r": br, "block_f": bf})
    elif op == "take_rows":
        (V, D) = shapes[0]
        for bd in _clamp_pow2ish(_BLOCK_D, D):
            if D % bd == 0 and 2 * bd * b <= VMEM_BYTES:
                out.append({"block_d": bd})
    else:
        raise KeyError("no tuning space for op %r" % (op,))
    if not out:
        out.append(default_config(op, shapes, dtype))
    return out


def default_config(op, shapes, dtype):
    """Heuristic config for untuned dispatch ('auto' tier cache miss)."""
    from .. import kernels
    return dict(kernels.kernel_module(op).DEFAULT_CONFIG)
