"""Bounded per-op config spaces for the kernel tuner (the TVM idea at
TPU scale: a SMALL searchable schedule space per op beats one fixed
kernel, and on TPU the only knobs that matter are tile/block shapes —
layout and vectorization belong to Mosaic).

Spaces are deterministic lists of plain dicts, filtered by hard VMEM
feasibility so the measuring path never launches a config Mosaic would
reject. ``default_config`` is the heuristic the dispatch layer uses when
the tuning cache has no entry ('auto' tier).
"""
from __future__ import annotations

__all__ = ["space_for", "default_config", "VMEM_BYTES"]

# per-core VMEM budget the tuner plans against (half of the 16 MiB v5e
# arsenal: Mosaic needs headroom for double-buffered DMA)
VMEM_BYTES = 8 * 1024 * 1024

_BLOCK_R = (8, 16, 32, 64, 128, 256, 512)
_BLOCK_S = (128, 256, 512, 1024, 2048)
_BLOCK_D = (128, 256, 512, 1024)
_BLOCK_A = (16, 32, 64, 128, 256, 512)   # attention q/kv tile rows


def _dtype_bytes(dtype):
    d = str(dtype)
    if "bfloat16" in d or "float16" in d:
        return 2
    if "8" in d:
        return 1
    return 4


def _clamp_pow2ish(options, limit):
    """Options no bigger than the first option >= limit (so tiny dims
    still get one covering block instead of an empty space)."""
    out = [o for o in options if o <= limit]
    bigger = [o for o in options if o > limit]
    if bigger:
        out.append(bigger[0])
    return out or [options[0]]


def space_for(op, shapes, dtype):
    """Deterministic list of candidate configs for (op, shapes, dtype).

    ``shapes`` is the tuple-of-shape-tuples the kernel's
    ``shape_key_shapes`` produced (the kernel's own canonical view).
    """
    b = _dtype_bytes(dtype)
    out = []
    if op == "bn_act":
        (R, S), = shapes[:1]
        for br in _clamp_pow2ish(_BLOCK_R, R):
            for bs in _clamp_pow2ish(_BLOCK_S, S):
                # x block + residual/out blocks (in+out+res) + coef column
                vmem = 3 * br * bs * b + 2 * br * 4 + br * bs * 4
                if vmem <= VMEM_BYTES:
                    out.append({"block_r": br, "block_s": bs})
    elif op == "scale_bias_act":
        (R, F), = shapes[:1]
        for br in _clamp_pow2ish(_BLOCK_R, R):
            for bf in _clamp_pow2ish(_BLOCK_S, F):
                vmem = 2 * br * bf * b + 2 * bf * 4 + br * bf * 4
                if vmem <= VMEM_BYTES:
                    out.append({"block_r": br, "block_f": bf})
    elif op == "take_rows":
        (V, D) = shapes[0]
        for bd in _clamp_pow2ish(_BLOCK_D, D):
            if D % bd == 0 and 2 * bd * b <= VMEM_BYTES:
                out.append({"block_d": bd})
    elif op == "flash_attn":
        # shapes = ((B*H, Tq, D), (B*H, Tk, D)); the knobs are the
        # online-softmax tile: q rows resident per step x KV rows streamed
        (BH, Tq, D) = shapes[0]
        Tk = shapes[1][1]
        for bq in _clamp_pow2ish(_BLOCK_A, Tq):
            for bk in _clamp_pow2ish(_BLOCK_A, Tk):
                # q + k + v + out tiles, plus f32 m/l/acc scratch
                vmem = (2 * bq * D + 2 * bk * D) * b \
                    + (2 * bq + bq * D) * 4 + bq * bk * 4
                if vmem <= VMEM_BYTES:
                    out.append({"block_q": bq, "block_k": bk})
    elif op == "flash_attn_paged":
        # shapes = ((S, W, H, Dh), (MP, page)); one knob — heads fused
        # per grid step (lane dim = block_h * Dh, DMAs get bigger and
        # the grid smaller as it grows). Must divide H, and the lane dim
        # must be Mosaic-valid: 128-aligned, or the full width (bh == H)
        (S, W, H, Dh) = shapes[0]
        (MP, page) = shapes[1]
        cands = sorted({bh for bh in (1, 2, 4, 8, 16)
                        if bh <= H and H % bh == 0
                        and (bh * Dh) % 128 == 0} | {H})
        for bh in cands:
            lanes = bh * Dh
            vmem = (2 * W * lanes + 2 * page * lanes) * b \
                + (2 * W * bh + W * lanes) * 4
            if vmem <= VMEM_BYTES:
                out.append({"block_h": bh})
    else:
        raise KeyError("no tuning space for op %r" % (op,))
    if not out:
        out.append(default_config(op, shapes, dtype))
    return out


def default_config(op, shapes, dtype):
    """Heuristic config for untuned dispatch ('auto' tier cache miss).
    Modules housing several tier ops expose ``default_config_for(op,
    shapes)`` (kernels/attention.py); single-op modules keep the plain
    ``DEFAULT_CONFIG`` attribute."""
    from .. import kernels
    mod = kernels.kernel_module(op)
    if hasattr(mod, "default_config_for"):
        return dict(mod.default_config_for(op, shapes))
    return dict(mod.DEFAULT_CONFIG)
