"""Measured kernel timings: the data that feeds ``LinearCostModel.fit``.

ROADMAP item 1 left one loop open: the chip-free cost model ships with
hand-rounded weights and "nothing feeds it yet". This module closes it.
When the tuner measures candidates **on-chip**, every (features,
wall-time) pair is appended to a JSONL log (``MXNET_KERNEL_TIMINGS``,
or ``$MXNET_TELEMETRY_DIR/kernel_timings.jsonl``); a later chip-free
``tools/autotune.py --recalibrate`` run loads the log, refits the
linear model with ordinary least squares, and reports how much the
model's *ranking* agrees with the measured ground truth before and
after — ranking is all the tuner needs from it (2008.01040's framing).

Row schema (one JSON object per line)::

    {"op": "bn_act", "key": "bn_act|8192x4096|bfloat16",
     "shapes": [[8192, 4096]], "dtype": "bfloat16",
     "config": {"block_r": 256, "block_s": 512},
     "features": {"hbm_time_us": ..., ...}, "time_us": 183.2,
     "device_kind": "TPU v5 lite", "wall_time": 1754380000.0}
"""
from __future__ import annotations

import itertools
import json
import os
import time

from . import cost_model as _cm
from .cache import shape_bucket_key

__all__ = ["timings_path", "record_rows", "load", "ranking_agreement",
           "recalibrate"]

REQUIRED = ("op", "shapes", "dtype", "config", "features", "time_us")


def timings_path():
    """Resolved timing-log path, or None when recording is disabled."""
    try:
        from mxnet_tpu.config import flags
        if flags.kernel_timings:
            return flags.kernel_timings
        if flags.telemetry_dir:
            return os.path.join(flags.telemetry_dir, "kernel_timings.jsonl")
    except Exception:
        pass
    return None


def record_rows(op, shapes, dtype, device_kind, rows, path=None):
    """Append the tuner's *measured* ranking rows to the timing log.
    No-op (returns 0) when no path is configured."""
    path = path or timings_path()
    if not path:
        return 0
    shapes = [list(s) for s in shapes]
    key = shape_bucket_key(op, tuple(tuple(s) for s in shapes), str(dtype))
    now = time.time()
    written = 0
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        for row in rows:
            if row.get("source") != "measured":
                continue
            f.write(json.dumps({
                "op": op, "key": key, "shapes": shapes,
                "dtype": str(dtype), "config": row["config"],
                "features": row["features"],
                "time_us": row["score_us"],
                "device_kind": device_kind, "wall_time": now,
            }) + "\n")
            written += 1
    return written


def load(path):
    """Parse a timing log; returns (rows, n_skipped). Lines that are not
    JSON objects with the full schema are counted, not fatal — a log
    that survived a mid-write kill should still recalibrate."""
    rows, skipped = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if (not isinstance(row, dict)
                    or any(k not in row for k in REQUIRED)
                    or any(k not in row["features"]
                           for k in _cm.FEATURE_NAMES)):
                skipped += 1
                continue
            rows.append(row)
    return rows, skipped


def _group_by_task(rows):
    keyed = {}
    for row in rows:
        key = row.get("key") or shape_bucket_key(
            row["op"], tuple(tuple(s) for s in row["shapes"]),
            str(row["dtype"]))
        keyed.setdefault(key, []).append(row)
    return keyed


def ranking_agreement(model, rows):
    """How well the model *ranks* measured rows, per tuning task.

    Returns ``{"pairwise": frac, "top1": frac, "tasks": {key: {...}}}``
    where pairwise is the fraction of (faster, slower) measured pairs
    the model orders the same way (ties in either ordering count half),
    and top1 is the fraction of tasks whose measured winner the model
    also ranks first.
    """
    tasks = {}
    agree = total = 0.0
    top1_hits = top1_tasks = 0
    for key, group in sorted(_group_by_task(rows).items()):
        if len(group) < 2:
            continue
        preds = [model.predict(r["features"]) for r in group]
        times = [float(r["time_us"]) for r in group]
        t_agree = t_total = 0.0
        for i, j in itertools.combinations(range(len(group)), 2):
            dt, dp = times[i] - times[j], preds[i] - preds[j]
            if dt == 0:
                continue
            t_total += 1
            if dp == 0:
                t_agree += 0.5
            elif (dt > 0) == (dp > 0):
                t_agree += 1
        measured_best = min(range(len(group)), key=lambda k: times[k])
        model_best = min(range(len(group)), key=lambda k: preds[k])
        top1 = measured_best == model_best
        top1_tasks += 1
        top1_hits += int(top1)
        agree += t_agree
        total += t_total
        tasks[key] = {
            "n": len(group),
            "pairwise": (t_agree / t_total) if t_total else 1.0,
            "top1": top1,
        }
    return {
        "pairwise": (agree / total) if total else 1.0,
        "top1": (top1_hits / top1_tasks) if top1_tasks else 1.0,
        "tasks": tasks,
    }


def recalibrate(rows, base_model=None):
    """Fit a fresh model on the measured rows and compare rankings.

    Returns ``(fitted_model, report)`` where report carries the
    before/after ``ranking_agreement`` summaries plus row counts; the
    caller (autotune CLI) renders it and decides whether to persist the
    fitted weights.
    """
    if not rows:
        raise ValueError("no usable timing rows to recalibrate from")
    base = base_model or _cm.default_model()
    before = ranking_agreement(base, rows)
    fitted = _cm.LinearCostModel().fit(
        [r["features"] for r in rows],
        [float(r["time_us"]) for r in rows])
    after = ranking_agreement(fitted, rows)
    report = {
        "rows": len(rows),
        "tasks": len(_group_by_task(rows)),
        "before": before,
        "after": after,
        "weights_before": base.to_dict(),
        "weights_after": fitted.to_dict(),
    }
    return fitted, report
