"""The auto-tuner: enumerate a bounded config space, rank, persist.

Offline only (``tools/autotune.py`` drives it) — the training/serving
hot path consults the resulting cache with a dict lookup and never calls
into this module.

Two ranking backends:

* **on-chip** (a real accelerator is attached): jit + warm up each
  candidate kernel on synthetic operands and take the best-of-k median
  wall time — ground truth, TVM-style.
* **chip-free** (CPU host, or ``--chip-free``): score every candidate
  with the static :mod:`cost_model`. Deterministic — identical rankings
  across runs is an acceptance criterion — and good enough to pick
  sane tiles because only the *order* matters.
"""
from __future__ import annotations

import time

from . import cost_model as _cm
from . import space as _space
from .cache import shape_bucket_key

__all__ = ["tune", "TuneResult"]


class TuneResult(dict):
    """dict with the fields: op, key, dtype, shapes, source, ranking
    (best first: {config, score_us, features}), best."""


def _runner(op, shapes, dtype, config):
    """Build a jitted synthetic-operand callable for one config (chip
    measurement path; compiled Mosaic, never interpret)."""
    import jax
    import jax.numpy as jnp
    from .. import kernels
    mod = kernels.kernel_module(op)
    jdt = jnp.dtype(dtype)
    if op == "bn_act":
        (R, S), = shapes[:1]
        x = jnp.zeros((R, S), jdt)
        sc = jnp.ones((R, 1), jnp.float32)
        sh = jnp.zeros((R, 1), jnp.float32)
        fn = jax.jit(lambda a: mod._epilogue(
            a, sc, sh, None, "relu", config["block_r"],
            config["block_s"], False))
        args = (x,)
    elif op == "scale_bias_act":
        (R, F), = shapes[:1]
        x = jnp.zeros((R, F), jdt)
        sc = jnp.ones((1, F), jnp.float32)
        b = jnp.zeros((1, F), jnp.float32)
        fn = jax.jit(lambda a: mod._call(
            a, sc, b, "gelu", config["block_r"], config["block_f"],
            False))
        args = (x,)
    elif op == "take_rows":
        (V, D) = shapes[0]
        (L,) = shapes[1]
        w = jnp.zeros((V, D), jdt)
        idx = jnp.arange(L, dtype=jnp.int32) % max(V, 1)
        fn = jax.jit(lambda a, i: mod._call(a, i, config["block_d"],
                                            False))
        args = (w, idx)
    elif op == "flash_attn":
        (BH, Tq, D) = shapes[0]
        Tk = shapes[1][1]
        q = jnp.zeros((BH, 1, Tq, D), jdt)
        kv = jnp.zeros((BH, 1, Tk, D), jdt)
        cfg = mod._Cfg(config["block_q"], config["block_k"],
                       Tq == Tk, False)       # causal when self-attention
        fn = jax.jit(lambda a, b_, c: mod._call(a, b_, c, cfg))
        args = (q, kv, kv)
    elif op == "flash_attn_paged":
        (S, W, H, Dh) = shapes[0]
        (MP, page) = shapes[1]
        n_pages = S * MP + 1                  # page 0 = scratch, like serve
        kv = jnp.zeros((n_pages * page, H * Dh), jdt)
        q = jnp.zeros((S, W, H * Dh), jdt)
        bt = (1 + jnp.arange(S * MP, dtype=jnp.int32)).reshape(S, MP)
        pos = jnp.full((S,), MP * page - 1, jnp.int32)   # worst-case ctx
        fn = jax.jit(lambda a, kp, vp, b_, p_: mod._paged_call(
            a, kp, vp, b_, p_, heads=H, page_size=page,
            block_h=config["block_h"], interpret=False))
        args = (q, kv, kv, bt, pos)
    else:
        raise KeyError("no tuner runner for op %r" % (op,))
    return fn, args


def _measure_us(fn, args, iters=20, repeats=3):
    out = fn(*args)
    jax_block(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax_block(out)
        best = min(best, (time.perf_counter() - t0) * 1e6 / iters)
    return best


def jax_block(out):
    import jax
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


def _config_key(config):
    return ",".join("%s=%s" % (k, config[k]) for k in sorted(config))


def tune(op, shapes, dtype, chip_free=None, model=None,
         device_kind=None, iters=20):
    """Rank every candidate config for (op, shapes, dtype).

    ``shapes`` is the kernel's canonical shape tuple-of-tuples (what
    ``<kernel>.shape_key_shapes`` returns). Returns a :class:`TuneResult`
    whose ``ranking`` is best-first and fully deterministic in chip-free
    mode (ties broken by config key).
    """
    import jax
    if chip_free is None:
        chip_free = jax.default_backend() == "cpu"
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = _cm.DEFAULT_DEVICE_KIND
    model = model or _cm.default_model()
    shapes = tuple(tuple(s) for s in shapes)
    candidates = _space.space_for(op, shapes, str(dtype))
    rows = []
    for config in candidates:
        feat = _cm.features(op, shapes, str(dtype), config, device_kind)
        if chip_free:
            score = model.predict(feat)
            source = "model"
        else:
            fn, args = _runner(op, shapes, dtype, config)
            score = _measure_us(fn, args, iters=iters)
            source = "measured"
        rows.append({"config": config, "score_us": float(score),
                     "features": feat, "source": source})
    rows.sort(key=lambda r: (r["score_us"], _config_key(r["config"])))
    if not chip_free:
        # feed the chip-free cost model: measured (features, time) pairs
        # land in the timing log for `autotune.py --recalibrate`
        from . import timings as _timings
        try:
            _timings.record_rows(op, shapes, str(dtype), device_kind, rows)
        except OSError:
            pass
    key = shape_bucket_key(op, shapes, str(dtype))
    return TuneResult(
        op=op, key=key, dtype=str(dtype),
        shapes=[list(s) for s in shapes],
        source=("model" if chip_free else "measured"),
        device_kind=device_kind,
        ranking=rows, best=rows[0])
