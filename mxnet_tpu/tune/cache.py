"""Versioned JSON tuning cache: offline winners, trace-time dict lookup.

``tools/autotune.py`` writes it; dispatch (``kernels/tier.py``) reads it.
The hot path never enumerates or scores anything — one canonical string
key per (op, shape-bucket, dtype), one dict lookup.

Shape bucketing: every dim rounds UP to the next power of two, so one
tuned entry covers the whole bucket (a config tuned for the padded
envelope is valid — if conservative — for everything inside it) and the
cache stays O(ops x log(shapes) x dtypes) instead of one row per shape
ever seen.

Versioning: the file carries ``format``/``version``; a mismatch (or
unparseable file) invalidates it WHOLESALE — dispatch silently falls
back to heuristic configs rather than trusting winners tuned for
different kernel generations. Bump ``SCHEMA_VERSION`` whenever a
kernel's config keys or tiling semantics change.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading

__all__ = ["SCHEMA_VERSION", "FORMAT", "TuningCache", "CacheRewriteError",
           "shape_bucket_key", "default_cache_path", "get_default",
           "invalidate_default", "lookup_config"]

SCHEMA_VERSION = 1
FORMAT = "mxnet-tpu-kernel-tuning"


class CacheRewriteError(ValueError):
    """An update would drop or rewrite committed winners without
    --allow-rewrite (the mxlint-baseline shrink-only discipline: tuning
    may only grow or deliberately improve, never silently regress)."""


def _bucket(n):
    n = int(n)
    if n <= 1:
        return 1
    p = 1
    while p < n:
        p <<= 1
    return p


def shape_bucket_key(op, shapes, dtype):
    """Canonical cache key, e.g. ``bn_act|8192x4096|bfloat16``."""
    parts = ["x".join(str(_bucket(d)) for d in shape) or "scalar"
             for shape in shapes]
    return "%s|%s|%s" % (op, ",".join(parts), str(dtype))


def default_cache_path():
    from ..config import flags
    p = str(flags.kernel_tuning_cache).strip()
    if p:
        return p
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "tools", "kernel_tuning.json")


class TuningCache:
    """In-memory view of one tuning-cache file."""

    def __init__(self, entries=None, path=None, version_ok=True):
        self.entries = dict(entries or {})
        self.path = path
        self.version_ok = version_ok

    @classmethod
    def load(cls, path):
        """Load; missing/corrupt/version-mismatched files come back empty
        (with ``version_ok`` False for the mismatch case so callers can
        report WHY lookups miss)."""
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return cls(path=path, version_ok=True)
        if not isinstance(raw, dict) or raw.get("format") != FORMAT \
                or raw.get("version") != SCHEMA_VERSION:
            return cls(path=path, version_ok=False)
        entries = raw.get("entries")
        if not isinstance(entries, dict):
            return cls(path=path, version_ok=False)
        return cls(entries=entries, path=path)

    def lookup(self, key):
        e = self.entries.get(key)
        if e is None:
            return None
        cfg = e.get("config")
        return dict(cfg) if isinstance(cfg, dict) else None

    def update_entries(self, new_entries, allow_rewrite=False):
        """Merge tuner output. Growth-guarded: existing keys may only
        change with ``allow_rewrite`` (and never silently vanish —
        merging cannot drop keys by construction)."""
        changed = []
        for key, entry in new_entries.items():
            old = self.entries.get(key)
            if old is not None and old.get("config") != entry.get("config") \
                    and not allow_rewrite:
                changed.append(key)
        if changed:
            raise CacheRewriteError(
                "refusing to rewrite %d committed tuning winner(s) "
                "without --allow-rewrite: %s"
                % (len(changed), ", ".join(sorted(changed))))
        self.entries.update(
            {k: dict(v) for k, v in new_entries.items()})
        return self

    def save(self, path=None):
        path = path or self.path
        payload = {"format": FORMAT, "version": SCHEMA_VERSION,
                   "entries": {k: self.entries[k]
                               for k in sorted(self.entries)}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def fingerprint(self):
        """Short stable hash of version+contents — engine caches and the
        CachedOp signature use it to notice re-tuning."""
        h = hashlib.sha256()
        h.update(("%s/%d" % (FORMAT, SCHEMA_VERSION)).encode())
        for k in sorted(self.entries):
            h.update(k.encode())
            h.update(json.dumps(self.entries[k], sort_keys=True).encode())
        return h.hexdigest()[:12]


# ------------------------------------------------------- process-wide view
_lock = threading.Lock()
_default = None
_default_path = None


def get_default():
    """The process-wide cache dispatch consults (memoized per path)."""
    global _default, _default_path
    path = default_cache_path()
    with _lock:
        if _default is None or _default_path != path:
            _default = TuningCache.load(path)
            _default_path = path
        return _default


def invalidate_default():
    """Forget the memoized cache (tests, or after autotune --update)."""
    global _default, _default_path
    with _lock:
        _default = None
        _default_path = None


def lookup_config(op, shapes, dtype):
    """Trace-time lookup -> (config-or-None, key). Pure dict access."""
    key = shape_bucket_key(op, shapes, dtype)
    return get_default().lookup(key), key
