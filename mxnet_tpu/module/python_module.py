"""Modules implemented directly in Python.

Parity with the reference's ``PythonModule`` / ``PythonLossModule``
(``python/mxnet/module/python_module.py:31,240``): a ``PythonModule`` is a
parameter-free stage presenting the BaseModule interface whose compute is
arbitrary user Python; ``PythonLossModule`` is the common case — a loss whose
gradient w.r.t. its input scores is supplied as ``grad_func`` — used as the
tail of a :class:`~.sequential_module.SequentialModule` chain.

TPU note: compute here runs eagerly on device via NDArray (jax under the
hood); a user needing the loss *inside* the compiled program should express
it symbolically instead.  This class exists for the reference's extension
workflow (e.g. losses that are easier to state as ``d loss / d scores``).
"""
import logging

from .base_module import BaseModule
from .. import ndarray as nd


class PythonModule(BaseModule):
    """Subclass and override ``forward``/``backward`` (and ``update`` if the
    module owns parameters) to implement a module in plain Python."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names) if label_names is not None else None
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.params_initialized = True  # parameter-free by default

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names if self._label_names is not None else []

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) --------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        pass

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        pass

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    # -- setup -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if grad_req != "write":
            raise ValueError("PythonModule only supports grad_req='write'")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        names = [x[0] for x in data_shapes]
        assert names == self._data_names, (names, self._data_names)
        self._data_shapes = list(data_shapes)

        self._label_shapes = list(label_shapes) if label_shapes else None
        if self._label_shapes is not None:
            assert self._label_names is not None
            assert [x[0] for x in self._label_shapes] == self._label_names

        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Return ``[(name, shape), ...]`` given bound data/label shapes."""
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_shapes is None:
            return  # no labels -> nothing to score
        if pre_sliced:
            raise RuntimeError("PythonModule does not support pre-sliced labels")
        eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """A loss stage: passes scores through on forward, emits
    ``grad_func(scores, labels)`` as the input gradient on backward."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        assert len(self._data_names) == 1
        assert len(self._label_names) == 1
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "loss module takes no out_grads"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is None:
            raise NotImplementedError(
                "pass grad_func or override _backward_impl")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, nd.NDArray):
            grad = nd.array(grad)
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError("no executors to monitor in a loss module")
