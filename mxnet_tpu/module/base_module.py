"""BaseModule: the training-loop surface.

Parity: ``python/mxnet/module/base_module.py`` (reference — ``fit`` loop
:500-560, ``score``, ``predict``, ``forward_backward``). The subclass Module
does the executor work; fit() here is intentionally the same epoch loop shape
as the reference so reference-era training scripts port unchanged.
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from .. import metric as _metric
from ..model import BatchEndParam
from ..base import MXNetError


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    def _ddp_stats(self, n_steps):
        """Per-window DDP telemetry payload for publish_window; Module
        overrides when the bucketed all-reduce path is engaged."""
        return None

    # ------------------------------------------------------------ high level
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def _fit_step(self, data_batch):
        """One fit-loop iteration: fwd+bwd then update. Subclasses may fuse
        the pair atomically (Module donates buffers to XLA here — in-place
        param/opt updates — which the public forward_backward()/update()
        contract, with its deferred commit, cannot allow)."""
        self.forward_backward(data_batch)
        self.update()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=eval_metric, locals=locals()))
            actual_num_batch += 1
        if score_end_callback:
            for cb in _as_list(score_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                 eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)]
                       for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        from ..ndarray import ndarray as _nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError("Cannot merge batches: different number "
                                     "of outputs per batch")
            output_list2 = [
                _nd.array(_np.concatenate(
                    [out[i].asnumpy() for out in output_list]))
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, steps_per_dispatch=None,
            checkpoint=None):
        """Epoch loop (reference base_module.py:410-560).

        ``steps_per_dispatch=K > 1`` groups K batches into ONE compiled
        XLA dispatch (`lax.scan` over the stacked feeds — see
        ``FusedStep.run_k``), amortising per-step host/PJRT latency.
        Metric updates stay per-batch; ``batch_end_callback`` fires per
        batch but only after its group completes; lr/wd schedules advance
        in steps of K. Requires a module with a fused grouped step
        (plain :class:`Module`) and no monitor.

        ``steps_per_dispatch=None`` (default) picks K automatically:
        ``flags.steps_per_dispatch`` (MXNET_STEPS_PER_DISPATCH, default
        16) when nothing in the loop needs per-step host attention —
        no monitor/batch_end_callback/checkpoint/sparse_row_id_fn/
        lr_scheduler, and the eval metric either absent or folded into
        the device step (see docs/perf.md "Async fit loop"). Otherwise
        falls back to K=1, reference per-step semantics.

        Completed dispatches are NOT waited on synchronously: a
        :class:`~mxnet_tpu.engine.DepthController`
        (``flags.engine_depth``, default 2) bounds the in-flight queue,
        and the loop blocks only at checkpoint snapshots, epoch
        boundaries, and metric reads.

        ``checkpoint``: a :class:`mxnet_tpu.checkpoint.CheckpointManager`
        enabling elastic training — full training state (params, optimizer
        trajectory, RNG chain, loop position) is snapshotted every
        ``save_every`` steps, and when the launcher sets
        ``MXNET_RESUME_DIR`` after a worker death, fit() restores the
        newest snapshot all ranks share and continues bitwise-identically
        to an uninterrupted run (see docs/fault_tolerance.md). Defaults to
        an env-constructed manager when ``MXNET_CHECKPOINT_DIR`` or
        ``MXNET_RESUME_DIR`` is set."""
        from .. import initializer as _init
        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = _init.Uniform(0.01)

        # validate an EXPLICIT steps_per_dispatch BEFORE any side effect
        # (bind/install_monitor/init_optimizer are not undone by the
        # raise); None = decide automatically after the module is set up
        explicit_k = steps_per_dispatch is not None
        if explicit_k:
            if steps_per_dispatch < 1:
                raise ValueError("steps_per_dispatch must be >= 1, got %r"
                                 % (steps_per_dispatch,))
            if steps_per_dispatch > 1:
                if not hasattr(self, "_fit_group"):
                    raise ValueError(
                        "steps_per_dispatch > 1 needs a module with a "
                        "grouped fused step (plain Module); %s has none"
                        % type(self).__name__)
                if monitor is not None or sparse_row_id_fn is not None:
                    raise ValueError(
                        "steps_per_dispatch > 1 is incompatible with "
                        "monitor / sparse_row_id_fn")

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if eval_metric is None and eval_data is not None and \
                validation_metric is None:
            raise ValueError(
                "eval_metric=None (benchmark mode) needs an explicit "
                "validation_metric when eval_data is given")
        if validation_metric is None:
            validation_metric = eval_metric
        # eval_metric=None: benchmark mode — no metric updates, so no
        # device->host sync per batch (the reference's --benchmark 1 path
        # still pays this; on a TPU tunnel it would dominate)
        if eval_metric is not None and \
                not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        # ---- elastic checkpointing (docs/fault_tolerance.md) ----
        from .. import checkpoint as _ckpt
        from ..parallel import faultinject as _fi
        ckpt = checkpoint if checkpoint is not None \
            else _ckpt.CheckpointManager.from_env()
        global_step = 0
        resume_epoch, resume_nbatch = begin_epoch, 0
        resume_cursor = None
        if ckpt is not None and _ckpt.CheckpointManager.should_resume():
            state, manifest = ckpt.restore_latest()
            mine = manifest["step"] if manifest is not None else -1
            common = self._common_resume_step(mine)
            if common >= 0 and common != mine:
                # cross-rank snapshot skew (a rank died between its own
                # save and a peer's): roll back to the newest step EVERY
                # rank has, or the post-resume allreduces would silently
                # mix different weight histories
                state, manifest = ckpt.restore(step=common)
            if common >= 0 and state is not None:
                resume_cursor = _ckpt.cursor_from_state(state)
                _ckpt.restore_module(self, state)
                global_step = manifest["step"]
                resume_epoch = manifest["epoch"]
                resume_nbatch = manifest["nbatch"]
                self.logger.info(
                    "resumed from checkpoint step %d (epoch %d, batch %d) "
                    "in %s", global_step, resume_epoch, resume_nbatch,
                    ckpt.directory)
            else:
                self.logger.warning(
                    "MXNET_RESUME_DIR set but no common valid checkpoint "
                    "across ranks — starting from scratch")
        meta = {"kvstore": kvstore if isinstance(kvstore, str)
                else getattr(kvstore, "type", None)}

        # ---- async loop setup (docs/perf.md "Async fit loop") ----
        # 1. fold the metric into the device step when its math allows:
        #    per-batch update_metric becomes a no-op on the proxy and the
        #    (sum, count) carry moves to host only at reads
        from ..config import flags as _flags
        if hasattr(self, "_engage_device_metric"):
            if eval_metric is not None and monitor is None:
                proxy = self._engage_device_metric(eval_metric)
                if proxy is not None:
                    eval_metric = proxy
            else:
                self._detach_device_metric()
        # 2. with no per-step host observer left, run K steps per dispatch
        #    (train-loop-under-scan); anything that must see the host
        #    between steps keeps the reference per-step loop
        if not explicit_k:
            auto_k = (monitor is None and sparse_row_id_fn is None
                      and batch_end_callback is None and ckpt is None
                      and hasattr(self, "_fit_group")
                      and getattr(self, "_fused", None) is not None
                      and (eval_metric is None or
                           getattr(eval_metric, "_device_resident", False))
                      and getattr(getattr(self, "_optimizer", None),
                                  "lr_scheduler", None) is None)
            steps_per_dispatch = max(1, int(_flags.steps_per_dispatch)) \
                if auto_k else 1
        grouped = steps_per_dispatch > 1
        # 3. dispatch without blocking; bound the in-flight queue so the
        #    host can't run unboundedly ahead of the chip
        from ..engine import DepthController
        depth_ctl = DepthController()

        # 4. run-wide telemetry (docs/observability.md): publish step
        #    time / throughput / live MFU / engine depth / sync census at
        #    K-step window boundaries, using ONLY values this frame
        #    already holds on the host (wall clock, batch shapes, the
        #    in-flight dispatch count) — zero extra device->host syncs,
        #    pinned by tests/test_step_sync_budget.py
        from .. import telemetry as _telemetry
        if _flags.telemetry_mfu and \
                "flops_per_step" not in _telemetry.run_info():
            flops_fn = getattr(self, "_fused_step_flops", None)
            flops = flops_fn() if flops_fn is not None else None
            if flops:
                _telemetry.set_run_info(flops_per_step=flops)
        _telem_t0 = time.monotonic()
        _telem_every = max(1, int(_flags.steps_per_dispatch))
        _telem_acc = [0, 0]          # per-step path: (steps, examples)

        # 5. streaming-tier window stats (docs/data.md): input stall (time
        #    the loop blocked on the iterator / staged feed), H2D bytes
        #    and feed-queue depth — all host-held values, zero extra
        #    device->host syncs (tests/test_step_sync_budget.py)
        _data_acc = [0.0, 0]         # (input_stall_ms, h2d_bytes)
        _queue_depth = [getattr(train_data, "queue_depth", None)]
        has_cursor = hasattr(train_data, "get_cursor") \
            and hasattr(train_data, "seek")
        data_cursor = [None]         # last CONSUMED batch's cursor

        def _timed_next(it):
            # blocking time on the iterator IS the loop's input stall
            t0 = time.monotonic()
            try:
                return next(it)
            finally:
                _data_acc[0] += (time.monotonic() - t0) * 1000.0

        def _batch_examples(b):
            try:
                return int(b.data[0].shape[0])   # host metadata, no sync
            except Exception:
                return 0

        def _batch_h2d_bytes(b):
            # host-side metadata only (shape x itemsize); never touches
            # device buffers
            try:
                n = 0
                for arrs in (b.data, b.label or []):
                    for a in arrs:
                        k = 1
                        for d in getattr(a, "shape", ()):
                            k *= int(d)
                        n += k * (getattr(getattr(a, "dtype", None),
                                          "itemsize", 4) or 4)
                return n
            except Exception:
                return 0

        def _telem_window(n_steps, examples, gstep):
            nonlocal _telem_t0
            now = time.monotonic()
            data = {"input_stall_ms": _data_acc[0],
                    "h2d_bytes": _data_acc[1]}
            qd_fn = _queue_depth[0]
            if qd_fn is not None:
                try:
                    data["queue_depth"] = qd_fn()
                except Exception:
                    pass
            _data_acc[0], _data_acc[1] = 0.0, 0
            _telemetry.publish_window(
                steps=n_steps, window_s=now - _telem_t0,
                examples=examples or None,
                engine_depth=len(depth_ctl._inflight),
                global_step=gstep,
                ddp=self._ddp_stats(n_steps),
                data=data)
            _telem_t0 = now

        def _snap_state():
            # quiesce first: a snapshot must capture a settled trajectory,
            # not buffers a still-running dispatch is about to donate away
            depth_ctl.quiesce()
            state = _ckpt.module_state(self)
            if data_cursor[0] is not None:
                # the iterator's consumed-position cursor rides the
                # snapshot so resume can seek instead of replaying batches
                state[_ckpt.DATA_CURSOR_KEY] = \
                    _ckpt.encode_cursor(data_cursor[0])
            return state

        for epoch in range(max(begin_epoch, resume_epoch), num_epoch):
            tic = time.time()
            if eval_metric is not None:
                eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            if ckpt is not None and epoch == resume_epoch and resume_nbatch:
                if resume_cursor is not None and has_cursor:
                    # cursor seek: O(1) re-position to the exact
                    # (epoch, shard, offset) the snapshot had consumed,
                    # instead of the O(nbatch) batch-skip replay below
                    train_data.seek(resume_cursor)
                    data_iter = iter(train_data)
                    data_cursor[0] = dict(resume_cursor)
                else:
                    # re-align the (deterministic, unshuffled-or-reseeded)
                    # iterator with the checkpointed loop position: the
                    # first resume_nbatch batches were consumed before the
                    # snapshot
                    for _ in range(resume_nbatch):
                        try:
                            next(data_iter)
                        except StopIteration:
                            break
                nbatch = resume_nbatch
            if grouped:
                # one dispatch per K batches; callbacks fire per batch
                # (from THIS frame, so BatchEndParam.locals matches the
                # per-step path) but only after the group's dispatch.
                # When the module exposes _stage_group, a StagedKFeed
                # pre-builds each window's stacked device feed on a feeder
                # thread (async H2D overlapped with the in-flight
                # dispatch) — the zero-stall K-step feed, docs/data.md.
                staged_feed = None
                if _flags.data_staged_feed \
                        and getattr(self, "_fused", None) is not None \
                        and self.optimizer_initialized \
                        and hasattr(self, "_stage_group"):
                    from ..data.feed import StagedKFeed
                    staged_feed = StagedKFeed(
                        data_iter, steps_per_dispatch, self._stage_group,
                        depth=max(2, int(_flags.data_feed_depth)),
                        cursor_fn=(train_data.get_cursor if has_cursor
                                   else None))
                    _queue_depth[0] = staged_feed.queue_depth
                try:
                    group, end_of_batch = [], False
                    staged, win_cursor = None, None
                    while not end_of_batch:
                        if staged_feed is not None:
                            t0 = time.monotonic()
                            try:
                                win = staged_feed.next_window()
                            except StopIteration:
                                win = None
                                end_of_batch = True
                            _data_acc[0] += \
                                (time.monotonic() - t0) * 1000.0
                            if win is not None:
                                group = list(win.batches)
                                staged = win.staged
                                win_cursor = win.cursor
                                _data_acc[1] += win.h2d_bytes
                                if len(group) < steps_per_dispatch:
                                    end_of_batch = True  # tail window
                        else:
                            try:
                                b = _timed_next(data_iter)
                                group.append(b)
                                _data_acc[1] += _batch_h2d_bytes(b)
                            except StopIteration:
                                end_of_batch = True
                        if len(group) == steps_per_dispatch or \
                                (end_of_batch and group):
                            _fi.fire("step", step=global_step)
                            if len(group) == steps_per_dispatch:
                                if staged is not None:
                                    self._fit_group(group, eval_metric,
                                                    staged=staged)
                                else:
                                    self._fit_group(group, eval_metric)
                                depth_ctl.admit(self._dispatch_handles())
                            else:
                                # tail: per-step path — reuses/compiles
                                # the single-step program instead of
                                # tracing a second scan variant for the
                                # odd group size
                                for b in group:
                                    self._fit_group([b], eval_metric)
                                    depth_ctl.admit(
                                        self._dispatch_handles())
                            for data_batch in group:
                                if batch_end_callback is not None:
                                    for cb in _as_list(batch_end_callback):
                                        cb(BatchEndParam(
                                            epoch=epoch, nbatch=nbatch,
                                            eval_metric=eval_metric,
                                            locals=locals()))
                                nbatch += 1
                            global_step += len(group)
                            if win_cursor is not None:
                                data_cursor[0] = win_cursor
                            elif has_cursor and staged_feed is None:
                                # fit is the only consumer here, so the
                                # iterator cursor IS the consumed position
                                data_cursor[0] = train_data.get_cursor()
                            _telem_window(len(group),
                                          sum(_batch_examples(b)
                                              for b in group), global_step)
                            if ckpt is not None:
                                ckpt.maybe_save(_snap_state, global_step,
                                                epoch=epoch, nbatch=nbatch,
                                                meta=meta)
                            group, staged, win_cursor = [], None, None
                finally:
                    if staged_feed is not None:
                        staged_feed.close()
                        _queue_depth[0] = getattr(train_data,
                                                  "queue_depth", None)
            else:
                end_of_batch = False
                try:
                    next_data_batch = _timed_next(data_iter)
                except StopIteration:
                    # resume landed exactly on this epoch's end
                    end_of_batch = True
                while not end_of_batch:
                    data_batch = next_data_batch
                    _data_acc[1] += _batch_h2d_bytes(data_batch)
                    if monitor is not None:
                        monitor.tic()
                    # global_step steps have completed (and, on the save
                    # grid, been checkpointed) — "kill@step=N" dies HERE,
                    # so the supervised restart resumes at exactly step N
                    _fi.fire("step", step=global_step)
                    self._fit_step(data_batch)
                    depth_ctl.admit(self._dispatch_handles())
                    # metric BEFORE prefetch/prepare (reference
                    # base_module.py:528-545): prepare() may switch the
                    # bucketing module to the NEXT batch's bucket, whose
                    # executor has no outputs yet
                    if eval_metric is not None:
                        self.update_metric(eval_metric, data_batch.label)
                    if has_cursor:
                        # capture BEFORE prefetching the next batch: the
                        # cursor must reflect batches CONSUMED, not the
                        # loop's read-ahead
                        data_cursor[0] = train_data.get_cursor()
                    try:
                        next_data_batch = _timed_next(data_iter)
                        self.prepare(next_data_batch,
                                     sparse_row_id_fn=sparse_row_id_fn)
                    except StopIteration:
                        end_of_batch = True
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        for cb in _as_list(batch_end_callback):
                            cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                             eval_metric=eval_metric,
                                             locals=locals()))
                    nbatch += 1
                    global_step += 1
                    _telem_acc[0] += 1
                    _telem_acc[1] += _batch_examples(data_batch)
                    if _telem_acc[0] >= _telem_every:
                        _telem_window(_telem_acc[0], _telem_acc[1],
                                      global_step)
                        _telem_acc = [0, 0]
                    if ckpt is not None:
                        ckpt.maybe_save(_snap_state, global_step,
                                        epoch=epoch, nbatch=nbatch,
                                        meta=meta)
            # epoch boundary: drain in-flight dispatches before the host
            # reads metrics/params (one explicit wait, not one per step)
            depth_ctl.quiesce()
            if _telem_acc[0]:    # flush the partial per-step window
                _telem_window(_telem_acc[0], _telem_acc[1], global_step)
                _telem_acc = [0, 0]
            for name, val in (eval_metric.get_name_value()
                              if eval_metric is not None else []):
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()
        if ckpt is not None:
            ckpt.wait()  # join an in-flight async save; surface errors

    @staticmethod
    def _common_resume_step(mine):
        """Newest checkpoint step EVERY rank can restore (allgather-min);
        -1 if any rank has none. Single-process: just ``mine``."""
        from ..parallel import dist as _dist
        if not _dist.initialized() or _dist.num_workers() <= 1:
            return mine
        steps = _np.asarray(_dist.allgather(_np.int64(mine)))
        return int(steps.min())

    def _dispatch_handles(self):
        """Device handles standing for the most recent dispatch, for
        :class:`~mxnet_tpu.engine.DepthController` back-pressure. An XLA
        output buffer becomes ready only when its whole program retires,
        so the first output handle suffices per dispatch."""
        try:
            outs = self.get_outputs()
        except Exception:
            return []
        return [o._data for o in outs[:1] if hasattr(o, "_data")]

    # ---------------------------------------------------------- to override
    @property
    def symbol(self):
        return self._symbol

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def install_monitor(self, mon):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def init_params(self, **kwargs):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
