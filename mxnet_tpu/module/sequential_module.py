"""Container module that chains several modules into one pipeline.

Parity with the reference's ``SequentialModule``
(``python/mxnet/module/sequential_module.py:28``): each sub-module is bound
with the previous module's output shapes as its data shapes, ``forward``
threads the batch through the chain, and ``backward`` threads gradients in
reverse (each stage's ``get_input_grads`` become the previous stage's
``out_grads``).  Meta flags per stage: ``take_labels`` routes the original
batch labels to that stage, ``auto_wiring`` renames the previous stage's
outputs to the stage's expected data names.

TPU note: each sub-module keeps its own fused/jit step; the chain itself is
plain Python, so stages may live on different shardings (the v1-style
"manual pipeline" use-case).  For a single fused program prefer composing
Symbols before binding one Module.
"""
import logging

from .base_module import BaseModule
from ..io.io import DataBatch
from ..initializer import Uniform


class SequentialModule(BaseModule):
    """Chain of modules; data flows first->last, gradients last->first."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        self._data_shapes = None
        self._label_shapes = None

    def add(self, module, **kwargs):
        """Append ``module``; returns ``self`` for chaining.

        Keyword meta: ``take_labels=True`` feeds the chain's labels to this
        stage; ``auto_wiring=True`` renames incoming arrays to the stage's
        ``data_names``.
        """
        for key in kwargs:
            if key not in self._meta_keys:
                raise ValueError("unknown meta %r (known: %s)"
                                 % (key, sorted(self._meta_keys)))
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        # Chain composition invalidates any previous bind.
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def label_names(self):
        names = []
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS):
                names.extend(mod.label_names)
        return names

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    # -- parameters --------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for mod in self._modules:
            arg, aux = mod.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        initializer = initializer if initializer is not None else Uniform(0.01)
        for mod in self._modules:
            mod.init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params, allow_missing=True,
                            force_init=force_init, allow_extra=True)

        # Cross-stage duplicate parameter names would silently desync on
        # update; refuse them up front (reference does the same check).
        seen = set()
        for mod in self._modules:
            arg, aux = mod.get_params()
            for name in list(arg) + list(aux):
                if name in seen:
                    raise ValueError(
                        "duplicate parameter %r across chained modules" % name)
                seen.add(name)
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        for mod in self._modules:
            mod.set_params(arg_params, aux_params, allow_missing=True,
                           force_init=force_init, allow_extra=True)
        self.params_initialized = True

    # -- bind / optimizer --------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise ValueError("shared_module not supported for SequentialModule")
        assert self._modules, "add modules before bind"

        self.binded = False
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else None

        my_inputs_need_grad = bool(inputs_need_grad or
                                   (for_training and len(self._modules) > 1))

        cur_shapes = list(data_shapes)
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            if meta.get(self.META_AUTO_WIRING):
                names = mod.data_names
                assert len(names) == len(cur_shapes)
                cur_shapes = [(name, shp) for name, (_, shp)
                              in zip(names, cur_shapes)]
            stage_labels = (self._label_shapes
                            if meta.get(self.META_TAKE_LABELS) else None)
            mod.bind(data_shapes=cur_shapes, label_shapes=stage_labels,
                     for_training=for_training,
                     inputs_need_grad=(inputs_need_grad if i == 0
                                       else my_inputs_need_grad),
                     force_rebind=force_rebind, grad_req=grad_req)
            cur_shapes = list(mod.output_shapes)
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = DataBatch(data=list(data_batch.data),
                          label=data_batch.label, pad=getattr(data_batch, "pad", None))
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            mod.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            batch = DataBatch(data=mod.get_outputs(),
                              label=(data_batch.label
                                     if self._metas[i + 1].get(self.META_TAKE_LABELS)
                                     else None),
                              pad=getattr(data_batch, "pad", None))

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in range(len(self._modules) - 1, -1, -1):
            mod = self._modules[i]
            mod.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = mod.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[0].get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS):
                mod.update_metric(eval_metric, labels, pre_sliced=pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._modules:
            mod.install_monitor(mon)
