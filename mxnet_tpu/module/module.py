"""Module: symbol + executor + optimizer intermediate-level trainer.

Parity: ``python/mxnet/module/module.py`` (reference :573 forward, :627
backward, :644 update) over DataParallelExecutorGroup. TPU-native design:
one Executor per module; data parallelism over multiple chips is SPMD inside
the executor's jitted program (mesh sharding), not N replicated executors —
the reference's executor_group slicing collapses into GSPMD. ``contexts``
may be a list for API parity; the first entry selects the mesh.
"""
from __future__ import annotations

import logging
import pickle

import numpy as _np

from .base_module import BaseModule, _as_list
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray
from .. import optimizer as _opt
from .. import kvstore as _kvstore
from ..model import save_checkpoint, load_checkpoint
from ..initializer import InitDesc


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = list(context)
        if group2ctxs:
            raise MXNetError(
                "group2ctxs manual device placement is not supported on "
                "TPU: use context=[...] (SPMD data parallelism) or "
                "parallel.SPMDTrainStep tensor parallelism instead")
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        self._compression_params = compression_params

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused = None
        self._fused_opt_state = None
        self._fused_pending = None
        self._fused_ran = False
        self._ddp = False
        self._monitor_installed = False
        # device-resident metrics (device_metric.py): the (sum, count)
        # carry rides the fused step; host sees it only on publish
        self._fused_met_state = None
        self._device_plan = None
        self._device_proxy = None
        self._device_met_version = 0

    # ------------------------------------------------------------ properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return list(zip(self._output_names,
                            [o.shape for o in self._exec.outputs]))
        # before the first forward: shapes from an abstract trace (no device
        # work) — needed by containers like SequentialModule at bind time
        return list(zip(self._output_names, self._exec._out_shapes()))

    # ------------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self._drop_fused()
        # reference parity (module.py bind): a rebind invalidates the
        # optimizer binding too — init_optimizer must run again (fit does),
        # which also re-engages the fused step for the new executor
        self.optimizer_initialized = False
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = _normalize_shapes(data_shapes, self._data_names)
        self._label_shapes = _normalize_shapes(label_shapes, self._label_names) \
            if label_shapes else []

        shape_kwargs = {}
        for desc in self._data_shapes + (self._label_shapes or []):
            shape_kwargs[desc[0]] = desc[1]
        # context=[c0, c1, ...] selects SPMD data parallelism: the executor
        # builds a 'dp' mesh over the devices, shards data/label on the
        # batch axis, replicates parameters, and GSPMD all-reduces the
        # gradients inside the compiled step (the reference's
        # DataParallelExecutorGroup + kvstore reduce, collapsed into XLA).
        ctx = self._context if len(self._context) > 1 else self._context[0]
        req = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or name in self._state_names:
                req[name] = "null"
            elif name in self._fixed_param_names:
                req[name] = "null"
            else:
                req[name] = grad_req if for_training else "null"
        from ..executor import simple_bind
        self._exec = simple_bind(
            self._symbol, ctx, grad_req=req,
            batch_args=self._data_names + self._label_names, **shape_kwargs)
        if self._arg_params is not None:
            self._exec.copy_params_from(self._arg_params, self._aux_params,
                                        allow_extra_params=True)

    # ------------------------------------------------------------ parameters
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing parameters"

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            elif initializer is not None:
                desc = InitDesc(name, self._get_var_attrs(name))
                initializer(desc, arr)
            elif not allow_missing:
                raise MXNetError("parameter %r missing and no initializer"
                                 % name)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                aux_params[name].copyto(arr)
            elif initializer is not None:
                desc = InitDesc(name, self._get_var_attrs(name))
                initializer(desc, arr)
        self.params_initialized = True
        self._params_dirty = False
        self._arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        self._aux_params = {n: self._exec.aux_dict[n] for n in self._aux_names}

    def _get_var_attrs(self, name):
        for node in self._symbol._topo():
            if node.is_variable and node.name == name:
                return dict(node.attrs)
        return {}

    def get_params(self):
        assert self.binded and self.params_initialized
        return ({k: v.copy() for k, v in self._arg_params.items()},
                {k: v.copy() for k, v in self._aux_params.items()})

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            # reference module.py: default rescale_grad = 1/batch_size so
            # sum-style loss heads (SoftmaxOutput) yield mean gradients
            if "rescale_grad" not in optimizer_params and self._data_shapes:
                batch_size = self._data_shapes[0][1][0]
                optimizer_params["rescale_grad"] = 1.0 / max(batch_size, 1)
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer = _opt.create(optimizer, sym=self._symbol,
                                    param_idx2name=idx2name,
                                    **optimizer_params)
        self._optimizer = optimizer

        kv = None
        update_on_kvstore = False
        if kvstore:
            if isinstance(kvstore, str):
                kv = _kvstore.create(kvstore)
            else:
                kv = kvstore
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            # update_on_kvstore: reference default for dist_* (optimizer
            # runs on the server). tpu_sync has no server — its gradient
            # all-reduce happens inside the compiled SPMD step (GSPMD psum
            # over the executor's mesh), so the update applies directly to
            # the executor's replicated weights via the updater path.
            update_on_kvstore = kv.type.startswith("dist")
            # MXNET_DDP=1 (tools/launch.py --ddp): the dist_sync gradient
            # exchange moves INSIDE the compiled step — bucketed lax.psum
            # over the dp mesh (parallel/ddp.py), optimizer replicated on
            # every rank. dist_async keeps the kvstore server path.
            if update_on_kvstore and not kv.type.endswith("async"):
                from ..parallel import ddp as _ddp
                if _ddp.enabled():
                    mesh = _ddp.process_mesh()
                    batch = (self._data_shapes[0][1][0]
                             if self._data_shapes else 0)
                    if mesh.size > 1 and batch % mesh.size == 0:
                        update_on_kvstore = False
                        self._ddp = True
                    elif mesh.size > 1:
                        self.logger.warning(
                            "MXNET_DDP: batch %d not divisible by dp "
                            "mesh size %d; falling back to the kvstore "
                            "path", batch, mesh.size)
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore

        if kv is not None:
            for i, name in enumerate(self._param_names):
                kv.init(name, self._arg_params[name])
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = _opt.get_updater(self._optimizer)
        self._init_fused_step(kv)
        self.optimizer_initialized = True

    def _drop_fused(self):
        """Invalidate the fused step (rebind/monitor), first mirroring its
        optimizer state into the eager Updater so momentum/moments survive."""
        if self._fused is not None:
            if self._fused_opt_state is not None and \
                    self._updater is not None:
                self._updater.states = self._fused.state_to_updater(
                    self._fused_opt_state)
            self._fused = None
            self._fused_opt_state = None
            self._fused_pending = None
            self._fused_ran = False
            self._detach_device_metric()

    def _init_fused_step(self, kv):
        """Build the fused one-program train step (module/fused.py) when it
        can faithfully replace the eager fwd/bwd/update path: tpu_sync
        kvstore (always), or local/no kvstore on a TPU context (auto)."""
        from ..config import flags as _flags
        self._fused = None
        self._fused_ran = False
        self._detach_device_metric()
        if not self.for_training or not _flags.module_fused_step:
            return
        if self.inputs_need_grad or self._monitor_installed:
            return
        kv_type = kv.type if kv is not None else None
        if self._update_on_kvstore:
            return  # optimizer runs on the (dist) kvstore server
        on_tpu = all(c.device_type == "tpu" for c in self._context)
        if not (kv_type == "tpu_sync" or self._ddp
                or (on_tpu and kv_type in (None, "local", "device"))):
            return
        # 'add' grad accumulation needs the eager grad buffers
        if any(self._exec._grad_req.get(n) == "add"
               for n in self._param_names):
            return
        if self._optimizer.fused_ops() is None:
            return
        # fp16 params need the eager multi-precision path (f32 master copy
        # per weight, optimizer.py:71-75) — fused state layout differs
        if any(self._exec.arg_dict[n].dtype != _np.float32
               for n in self._param_names):
            return
        from .fused import FusedStep
        # multi_precision on a TPU module = bf16 compute over f32 master
        # weights (the reference's fp16 multi-precision SGD, optimizer.py
        # :452, mapped to the MXU's native dtype); the session dtype policy
        # (MXNET_COMPUTE_DTYPE, config.compute_dtype) can force or veto it
        default_cdt = None
        if getattr(self._optimizer, "multi_precision", False):
            import jax.numpy as _jnp
            default_cdt = _jnp.bfloat16
        from .. import config as _config
        compute_dtype = _config.compute_dtype(default=default_cdt)
        ddp_mesh = None
        if self._ddp:
            from ..parallel import ddp as _ddp
            ddp_mesh = _ddp.process_mesh()
        self._fused = FusedStep(self._exec, self._optimizer,
                                self._param_names,
                                compute_dtype=compute_dtype,
                                data_names=self._data_names,
                                keep_f32=self._norm_stat_params(),
                                ddp_mesh=ddp_mesh)
        self._fused_opt_state = self._fused.init_state()

    def _fused_step_flops(self):
        """Chip-free FLOPs of one fused step via XLA cost analysis, for
        the live MFU telemetry gauge. Pays a lowering, so only the
        MXNET_TELEMETRY_MFU=1 path in fit() calls it (bench.py supplies
        flops via telemetry.set_run_info instead); None when no fused
        step is bound or the backend has no cost model."""
        if self._fused is None or self._exec is None:
            return None
        try:
            ex = self._exec
            cost = self._fused.cost_analysis(
                ex._arg_vals(), ex._aux_vals(), self._fused_opt_state)
            if cost and cost.get("flops", 0) > 0:
                return float(cost["flops"])
        except Exception:
            pass
        return None

    def _ddp_stats(self, n_steps):
        """Host-held DDP bucket/comm summary scaled to a telemetry window
        of ``n_steps`` (base_module._telem_window). Pure bookkeeping from
        the reducer's static plan — ZERO device syncs, so the ≤1
        d2h-per-window budget is untouched. None when DDP is off."""
        if not self._ddp or self._fused is None:
            return None
        s = self._fused.ddp_stats()
        if s is None:
            return None
        return {"buckets": s["buckets"],
                "comm_bytes": s["comm_bytes"] * max(int(n_steps), 0),
                "overlap_ms": s["overlap_ms"]}

    def _norm_stat_params(self):
        """Names of params that must stay f32 under a low-precision compute
        policy: BatchNorm gamma/beta. The bf16-native BN kernel keeps its
        statistics/scale math in f32 and consumes f32 affine params
        directly (ops/nn.py), so downcasting them would only add converts
        back at every BN boundary."""
        keep = set()
        try:
            for node in self._symbol._topo():
                if node.op is not None and node.op.name == "BatchNorm":
                    for slot in (1, 2):  # gamma, beta inputs
                        if slot < len(node.inputs):
                            src = node.inputs[slot][0]
                            if src.is_variable:
                                keep.add(src.name)
        except Exception:
            pass
        return frozenset(keep)

    # --------------------------------------------------------------- running
    def _feed(self, data_batch):
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if self._label_shapes and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        return feed

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._exec.forward(is_train=is_train, **self._feed(data_batch))

    def forward_backward(self, data_batch):
        """fit's per-batch entry. On the fused path this launches ONE
        compiled program (fwd+bwd+reduce+optimizer update); the parameter/
        optimizer-state commit is deferred to update(). Bare forward()/
        backward() always take the eager path, so custom training loops see
        reference semantics (weights never move before update())."""
        if self._fused is not None and self.optimizer_initialized:
            self._forward_fused(self._feed(data_batch))
        else:
            self.forward(data_batch, is_train=True)
            self.backward()

    def _fit_step(self, data_batch):
        """Atomic fused fit step: one donating XLA program updates params/
        aux/optimizer state IN PLACE (no HBM double-buffering), and the
        results commit immediately. Falls back to the eager pair when the
        fused step is not engaged."""
        if self._fused is not None and self.optimizer_initialized:
            from .. import profiler as _profiler
            if _profiler.is_active("symbolic"):
                with _profiler.op_timer(
                        "Module::fused_fit_step", "symbolic",
                        lambda: [o._data for o in self._exec.outputs]):
                    return self._fit_step_fused_impl(data_batch)
            return self._fit_step_fused_impl(data_batch)
        else:
            self.forward_backward(data_batch)
            self.update()

    def _commit_fused(self, last_outs, new_params, new_aux, new_opt,
                      n_steps=1, new_met=None):
        """Commit a donating fused dispatch: the input buffers are dead, so
        params/aux/opt-state/outputs must all be adopted now. Shared by the
        per-step and grouped (run_k) paths — the commit protocol must stay
        identical."""
        from ..ndarray.ndarray import NDArray
        ex = self._exec
        for k, v in new_aux.items():
            ex.aux_dict[k]._rebind(v)
        for k in self._fused.param_names:
            ex.arg_dict[k]._rebind(new_params[k])
        ex.outputs = [NDArray(o, ctx=ex._ctx) for o in last_outs]
        ex._pending = None
        self._fused_opt_state = new_opt
        for _ in range(n_steps):
            self._fused.commit_counts()
        self._params_dirty = True
        self._fused_pending = None
        self._fused_ran = False
        if new_met is not None:
            # donated carry: the old device buffers are dead, adopt now
            self._fused_met_state = new_met
            self._device_met_version += 1

    def _fit_step_fused_impl(self, data_batch):
        from .. import random as _random
        ex = self._exec
        ex.set_inputs(**self._feed(data_batch))
        key = _random.next_key()
        outs, new_args, new_aux, new_opt, new_met = self._fused.run(
            ex._arg_vals(), ex._aux_vals(), self._fused_opt_state, key,
            donate=True, met_state=self._fused_met_state)
        self._commit_fused(outs, new_args, new_aux, new_opt,
                           new_met=new_met)

    def _fit_group(self, data_batches, eval_metric=None, staged=None):
        """fit's grouped entry (``steps_per_dispatch``): run the batches
        through :meth:`_fit_step_k`, then update ``eval_metric`` once per
        sub-batch from the stacked per-step outputs — metric semantics
        identical to the per-step loop. ``staged`` is an optional
        pre-built device feed from :meth:`_stage_group` (the zero-stall
        staged K-step feed, mxnet_tpu/data/feed.py)."""
        if self._fused is None or not self.optimizer_initialized \
                or len(data_batches) == 1:
            if len(data_batches) > 1 and \
                    not getattr(self, "_warned_group_fallback", False):
                self._warned_group_fallback = True
                self.logger.warning(
                    "steps_per_dispatch: fused step not engaged "
                    "(optimizer/kvstore/grad_req unfusable?) — falling "
                    "back to one dispatch per batch")
            for b in data_batches:
                self._fit_step(b)
                if eval_metric is not None:
                    self.update_metric(eval_metric, b.label)
            return
        from ..ndarray.ndarray import NDArray
        outs = self._fit_step_k(data_batches, staged=staged)
        if getattr(eval_metric, "_device_resident", False):
            return  # accumulated inside the scan body; nothing to replay
        if eval_metric is not None:
            ex = self._exec
            last = ex.outputs
            for i, b in enumerate(data_batches):
                ex.outputs = [NDArray(o[i], ctx=ex._ctx) for o in outs]
                self.update_metric(eval_metric, b.label)
            ex.outputs = last

    def _stage_group(self, data_batches):
        """Stage one K-step window's device feed ahead of dispatch (the
        ``stage_fn`` hook of :class:`mxnet_tpu.data.feed.StagedKFeed`).
        Runs on the feeder thread while the previous window is still in
        flight: per-batch cast via ``prepare_input`` then the SAME
        cast/stack/commit ``run_k`` would apply (``stack_feeds``), so the
        staged window is bitwise-identical to the unstaged path. Returns
        ``(payload, h2d_bytes)``; the payload carries both the stacked
        scan feed and the pre-cast last feed for the executor rebind.
        Only reads executor metadata (dtypes/sharding) — thread-safe
        against the main loop, which only commits donated outputs."""
        ex = self._exec
        place_each = ex._mesh is None
        feeds = [{name: ex.prepare_input(name, arr, place=place_each)
                  for name, arr in self._feed(b).items()}
                 for b in data_batches]
        nbytes = 0
        for b in data_batches:
            for arrs in (b.data, b.label or []):
                for a in arrs:
                    shape = getattr(a, "shape", ())
                    n = 1
                    for d in shape:
                        n *= int(d)
                    itemsize = getattr(
                        getattr(a, "dtype", None), "itemsize", 4) or 4
                    nbytes += n * itemsize
        return {"stacked": self._fused.stack_feeds(feeds),
                "last": feeds[-1]}, nbytes

    def _fit_step_k(self, data_batches, staged=None):
        """K fit steps in ONE donating XLA dispatch (`FusedStep.run_k` —
        the train-loop-under-scan TPU idiom). Caller (:meth:`_fit_group`)
        guarantees the fused step is engaged and K > 1. Returns the
        stacked per-step output values (list of ``(K, ...)`` jax arrays)
        so the fit loop can update metrics per sub-batch."""
        assert self._fused is not None and self.optimizer_initialized \
            and len(data_batches) > 1
        from .. import profiler as _profiler
        if _profiler.is_active("symbolic"):
            with _profiler.op_timer(
                    "Module::fused_fit_step_k", "symbolic",
                    lambda: [o._data for o in self._exec.outputs]):
                return self._fit_step_k_impl(data_batches, staged=staged)
        return self._fit_step_k_impl(data_batches, staged=staged)

    def _fit_step_k_impl(self, data_batches, staged=None):
        from .. import random as _random
        ex = self._exec
        if staged is not None:
            # pre-staged by _stage_group on the feeder thread; the stacked
            # buffer is already cast + committed to the device layout
            feeds = staged["stacked"]
            last = staged["last"]
            place_each = ex._mesh is None
        else:
            # each feed value gets the SAME cast (+ placement) set_inputs
            # applies (host iterator batches are cpu-committed; stacking
            # them raw would hand the donating jit cpu feeds next to
            # device params). Under a mesh, run_k re-commits the STACKED
            # array to P(None, 'dp') anyway, so per-slice placement would
            # be paid twice — skip it.
            place_each = ex._mesh is None
            feeds = [{name: ex.prepare_input(name, arr, place=place_each)
                      for name, arr in self._feed(b).items()}
                     for b in data_batches]
            last = feeds[-1]
        # keep the executor's input bindings current (shape checks, later
        # forward() calls) without re-casting/re-transferring the batch
        for name, val in last.items():
            ex.arg_dict[name]._rebind(
                val if place_each else ex._place_input(val, name))
        keys = [_random.next_key() for _ in data_batches]
        outs, new_params, new_aux, new_opt, new_met = self._fused.run_k(
            ex._arg_vals(), ex._aux_vals(), self._fused_opt_state,
            feeds, keys, met_state=self._fused_met_state)
        self._commit_fused([o[-1] for o in outs], new_params, new_aux,
                           new_opt, n_steps=len(data_batches),
                           new_met=new_met)
        return outs

    # ------------------------------------------------- device-resident metric
    def _engage_device_metric(self, eval_metric):
        """Fold ``eval_metric``'s accumulation into the fused step
        (device_metric.py): returns a :class:`DeviceMetricProxy` for fit's
        loop, or None when the metric's math can't be replicated on device
        / the fused step isn't engaged (caller keeps the per-batch host
        path)."""
        from ..config import flags as _flags
        if self._fused is None or not _flags.device_metrics:
            self._detach_device_metric()
            return None
        if self._ddp:
            # under check_rep=False a replicated metric carry would
            # silently accumulate only each rank's LOCAL batches — keep
            # the host metric path (per-worker metric, reference
            # dist_sync semantics)
            self._detach_device_metric()
            return None
        if eval_metric is None \
                or getattr(eval_metric, "_device_resident", False):
            self._detach_device_metric()
            return None
        from .. import device_metric as _dm
        out_names = list(self._output_names)
        label_names = list(self._label_names)
        plan = _dm.plan_for(eval_metric, out_names, label_names)
        if plan is None:
            # a previous fit() may have attached a met_fn for a different
            # metric; a stale carry would ride every step for nothing
            self._detach_device_metric()
            return None

        def met_fn(state, outs, rest):
            pred_dict = dict(zip(out_names, outs))
            label_dict = {k: rest[k] for k in label_names if k in rest}
            return plan.update(state, label_dict, pred_dict)

        self._device_plan = plan
        self._fused.attach_metric(met_fn)
        self._fused_met_state = self._place_met_state(plan.init_state())
        self._device_met_version += 1
        proxy = _dm.DeviceMetricProxy(self, eval_metric)
        proxy._pub_version = self._device_met_version
        self._device_proxy = proxy
        return proxy

    def _place_met_state(self, state):
        """Commit a fresh metric carry to the mesh's replicated sharding
        (single-device modules take the host scalars as-is; jit places
        them)."""
        ex = self._exec
        if ex._mesh is None:
            return state
        import jax
        return tuple(tuple(jax.device_put(x, ex._rep_sharding) for x in p)
                     for p in state)

    def _reset_device_metric(self):
        """Zero the device carry. Safe mid-flight at any engine depth: the
        in-flight dispatches already consumed the old (donated) handles,
        and the next dispatch picks up the fresh zeros."""
        if self._device_plan is None:
            return
        self._fused_met_state = self._place_met_state(
            self._device_plan.init_state())
        self._device_met_version += 1

    def _publish_device_metric(self):
        """ONE device->host fetch of the whole metric carry, written into
        the wrapped metric's host accumulators. This is the only d2h the
        device-metric path pays, and only when someone reads the metric."""
        if self._device_plan is None or self._fused_met_state is None:
            return
        pending = [x for p in self._fused_met_state for x in p
                   if hasattr(x, "block_until_ready")]
        host = self._fused_met_state
        if pending:
            from .. import profiler as _profiler
            _profiler.record_host_sync(
                "d2h", sum(int(getattr(x, "nbytes", 0)) for x in pending))
            import jax
            host = jax.device_get(self._fused_met_state)
        self._device_plan.publish(host)

    def _detach_device_metric(self):
        if self._fused is not None:
            self._fused.detach_metric()
        self._fused_met_state = None
        self._device_plan = None
        self._device_proxy = None

    def _forward_fused(self, feed):
        from .. import random as _random
        from ..ndarray.ndarray import NDArray
        ex = self._exec
        ex.set_inputs(**feed)
        key = _random.next_key()
        # met_state=None: the public forward_backward path never touches
        # metric accumulation (the caller updates its metric by hand)
        outs, new_args, new_aux, new_opt, _ = self._fused.run(
            ex._arg_vals(), ex._aux_vals(), self._fused_opt_state, key)
        # aux (BN stats) commit at forward time, like the eager path
        for k, v in new_aux.items():
            ex.aux_dict[k]._rebind(v)
        ex.outputs = [NDArray(o, ctx=ex._ctx) for o in outs]
        ex._pending = None
        # params/opt state commit only in update(): a skipped update()
        # (e.g. NaN-loss guard) must leave weights and the LR schedule
        # untouched, as in the eager path
        self._fused_pending = (new_args, new_opt)
        self._fused_ran = True

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._fused_ran:
            new_args, new_opt = self._fused_pending
            ex = self._exec
            for k in self._fused.param_names:
                ex.arg_dict[k]._rebind(new_args[k])
            self._fused_opt_state = new_opt
            self._fused.commit_counts()
            self._fused_pending = None
            self._fused_ran = False
            return
        if self._update_on_kvstore and self._kvstore is not None:
            for name in self._param_names:
                grad = self._exec.grad_dict.get(name)
                if grad is None:
                    continue
                self._kvstore.push(name, grad)
                # weights must always come back, even from a sparse store
                self._kvstore.pull(name, self._exec.arg_dict[name],
                                   ignore_sparse=False)
        else:
            if self._ddp:
                # eager DDP fallback (optimizer without a fused form):
                # the backward is already done so there is nothing left
                # to overlap with, but the exchange is still ONE bucketed
                # collective per dtype-bucket instead of one per tensor
                from ..parallel import dist as _dist
                names = [n for n in self._param_names
                         if self._exec.grad_dict.get(n) is not None]
                reduced = _dist.allreduce_tree(
                    [self._exec.grad_dict[n]._data for n in names])
                for n, g in zip(names, reduced):
                    self._exec.grad_dict[n]._rebind(g)
            for i, name in enumerate(self._param_names):
                grad = self._exec.grad_dict.get(name)
                if grad is None:
                    continue
                self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def get_states(self, merge_multi_context=True):
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        for name, v in zip(self._state_names, states or []):
            v.copyto(self._exec.arg_dict[name])

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if labels:
            eval_metric.update_dict(
                dict(zip(self._label_names, labels)),
                dict(zip(self._output_names, self._exec.outputs)))
        else:
            eval_metric.update_dict(
                {}, dict(zip(self._output_names, self._exec.outputs)))

    def install_monitor(self, mon):
        # monitors watch per-op values — incompatible with the fused
        # whole-step program, so its construction is skipped (or dropped,
        # preserving accumulated optimizer state)
        self._monitor_installed = True
        self._drop_fused()
        mon.install(self._exec)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
        else:
            if self._fused is not None and self._fused_opt_state is not None:
                # fused state is authoritative; mirror into the updater
                # layout so the on-disk format matches the eager path
                self._updater.states = self._fused.state_to_updater(
                    self._fused_opt_state)
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
            if self._fused is not None:
                self._fused_opt_state = self._fused.state_from_updater(
                    self._updater.states)

    # ------------------------------------------------- elastic checkpointing
    def _live_updater(self):
        """The Updater currently applying updates: the kvstore's when the
        optimizer runs on the (dist) kvstore, ours otherwise. None on the
        dist_async path (state lives in the server process)."""
        if self._update_on_kvstore and self._kvstore is not None:
            return getattr(self._kvstore, "_updater", None)
        return self._updater

    def _optimizer_state_bytes(self):
        """Opaque blob of the full optimizer trajectory for
        CheckpointManager: momentum/moment buffers (updater states) plus
        the update counters that drive lr schedules and Adam bias
        correction. Restored by ``_set_optimizer_state_bytes`` WITHOUT
        replacing the live optimizer object, so fused-step and kvstore
        closures over it stay valid."""
        if not self.optimizer_initialized:
            return None
        updater = self._live_updater()
        states_blob = None
        if updater is not None:
            if self._fused is not None and \
                    self._fused_opt_state is not None:
                updater.states = self._fused.state_to_updater(
                    self._fused_opt_state)
            states_blob = updater.get_states(dump_optimizer=False)
        opt = self._optimizer
        return pickle.dumps({
            "states": states_blob,
            "num_update": opt.num_update,
            "index_counts": dict(opt._index_update_count),
        }, protocol=2)

    def _set_optimizer_state_bytes(self, blob):
        if not self.optimizer_initialized or blob is None:
            return
        obj = pickle.loads(bytes(blob))
        updater = self._live_updater()
        if updater is not None and obj.get("states") is not None:
            updater.set_states(obj["states"])
            if self._fused is not None:
                self._fused_opt_state = self._fused.state_from_updater(
                    updater.states)
        # counters are copied INTO the live optimizer (not pickled over
        # it): the kvstore updater and fused step hold references to this
        # exact object
        opt = self._optimizer
        opt.num_update = obj["num_update"]
        opt._index_update_count.clear()
        opt._index_update_count.update(obj["index_counts"])

    def _sync_params_to_kvstore(self):
        """Make the kvstore's weight copy match the executor's.

        On dist_sync the AUTHORITATIVE weights live in ``kv._store`` (push
        updates them there, update() pulls them back) — restoring only the
        executor would be overwritten by the first post-resume pull."""
        kv = self._kvstore
        if kv is None or not self.binded:
            return
        if getattr(kv, "_async_client", None) is not None:
            return  # dist_async: the server's weights are authoritative
        for name in self._param_names:
            if name in kv._store:
                kv._store[name] = self._exec.arg_dict[name].copy()

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        arg_params, aux_params = self.get_params()
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, force_init=True)


def _normalize_shapes(shapes, names):
    """Accept DataDesc list, (name, shape) list, or dict."""
    if shapes is None:
        return []
    out = []
    for item in shapes:
        if hasattr(item, "name") and hasattr(item, "shape"):
            out.append((item.name, tuple(item.shape)))
        elif isinstance(item, (tuple, list)):
            out.append((item[0], tuple(item[1])))
        else:
            raise TypeError("bad shape spec %r" % (item,))
    return out
