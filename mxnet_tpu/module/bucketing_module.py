"""BucketingModule: variable-length sequence training.

Parity: ``python/mxnet/module/bucketing_module.py``. TPU-native note: each
bucket is a distinct static shape → a distinct jitted XLA program sharing
parameter storage — exactly the shape-keyed compile-cache strategy
SURVEY.md §7 hard-part 1 prescribes (the reference invented bucketing for
the same reason: avoid re-binding per length).
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module
from ..base import MXNetError


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        if group2ctxs:
            raise MXNetError(
                "group2ctxs manual device placement is not supported on "
                "TPU: use context=[...] SPMD data parallelism instead")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None
        self._grad_req = "write"

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names, label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    def get_params(self):
        assert self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True
        self._params_dirty = False

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, grad_req=self._grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        grad_req=self._grad_req)
            if self.params_initialized:
                arg_params, aux_params = self.get_params()
                module.init_params(arg_params=arg_params, aux_params=aux_params,
                                   force_init=True, allow_missing=False)
            if self.optimizer_initialized:
                cur = self._curr_module
                module._optimizer = cur._optimizer
                module._updater = cur._updater
                module._kvstore = cur._kvstore
                module._update_on_kvstore = cur._update_on_kvstore
                module.optimizer_initialized = True
                # fused one-program step per bucket (each bucket compiles
                # once — the bucketing contract); optimizer STATE is
                # mirrored across buckets below, so momentum stays one
                # accumulator per weight like the reference's shared
                # Updater
                module._init_fused_step(cur._kvstore)
            self._buckets[bucket_key] = module
        prev = self._curr_module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        if prev is not None and prev is not self._curr_module \
                and self.optimizer_initialized:
            self._sync_fused_opt_state(prev, self._curr_module)
        if self.params_initialized:
            # share the canonical parameter arrays across buckets
            default = self._buckets[self._default_bucket_key]
            if self._curr_module is not default:
                arg_params, aux_params = default.get_params()
                self._curr_module.init_params(arg_params=arg_params,
                                              aux_params=aux_params,
                                              force_init=True)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod._kvstore = self._curr_module._kvstore
                mod._update_on_kvstore = self._curr_module._update_on_kvstore
                mod.optimizer_initialized = True
                mod._init_fused_step(self._curr_module._kvstore)
        self.optimizer_initialized = True

    @staticmethod
    def _sync_fused_opt_state(prev, cur):
        """One optimizer accumulator per weight across buckets: the
        shared eager Updater is the interchange format — a fused module
        mirrors its state out on switch-away and the next fused module
        adopts it on switch-in (no recompile; state-only)."""
        if prev._fused is not None and prev._fused_opt_state is not None \
                and prev._updater is not None:
            prev._updater.states = prev._fused.state_to_updater(
                prev._fused_opt_state)
        if cur._fused is not None and cur._updater is not None \
                and cur._updater.states:
            cur._fused_opt_state = cur._fused.state_from_updater(
                cur._updater.states)

    def _sync_params_to_default(self):
        """The default bucket carries the canonical parameters other
        buckets re-sync from on switch."""
        default = self._buckets[self._default_bucket_key]
        if self._curr_module is not default:
            arg_params, aux_params = self._curr_module.get_params()
            default.init_params(arg_params=arg_params,
                                aux_params=aux_params, force_init=True)

    def _fit_step(self, data_batch):
        """Fit-loop iteration through the CURRENT bucket's fused step
        (falls back to eager inside Module._fit_step), preserving the
        default-bucket parameter sync that update() performs."""
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._params_dirty = True
        self._curr_module._fit_step(data_batch)
        self._sync_params_to_default()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        bucket_key = data_batch.bucket_key
        original_bucket_key = self._curr_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_bucket_key = bucket_key

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._params_dirty = True
        self._curr_module.update()
        self._sync_params_to_default()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def get_states(self, merge_multi_context=True):
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        self._curr_module.set_states(states, value)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        if save_optimizer_states:
            # the ACTIVE bucket holds the freshest fused optimizer state;
            # mirror it into the shared Updater BEFORE the default bucket
            # snapshots (its own fused state is stale since the last
            # switch), or resumed momentum silently restarts from the
            # switch point
            cur = self._curr_module
            default = self._buckets[self._default_bucket_key]
            if cur is not default:
                self._sync_fused_opt_state(cur, default)
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
