"""Fused Module train step: fwd + bwd + gradient reduce + optimizer update
in ONE XLA program, reachable from the product API.

Round-2 gap (VERDICT): ``SPMDTrainStep`` existed but only bench.py called
it; ``Module.update`` ran one eager dispatch per parameter per step with the
optimizer outside the compiled program. This module closes that gap: when a
``tpu_sync`` kvstore is attached (or automatically on TPU with a local
kvstore), :class:`Module` builds a :class:`FusedStep` from its bound
:class:`Executor` and its :class:`Optimizer` and drives every
``fit`` iteration through it.

Reference semantics being collapsed (citations into /root/reference):

* ``update_on_kvstore`` dispatch — python/mxnet/model.py:123-170;
* per-parameter update ops — src/operator/optimizer_op.cc;
* gradient reduce — src/kvstore/comm.h (CommDevice): here GSPMD inserts the
  psum over the executor's 'dp' mesh inside the same program.

Dynamic hyperparameters (lr, wd, rescale_grad, update count t) enter as
traced scalars/vectors, so LR schedules never trigger recompilation.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def _downcast_group(leaves, cdt):
    """Cast a list of f32 arrays to ``cdt`` with ONE convert op: flatten,
    concatenate, convert, split. A naive per-leaf ``astype`` emits one
    f32->cdt convert per parameter in the lowered program (and one
    cdt->f32 per gradient on the way back); grouping keeps the convert
    count O(1) in the number of parameters, which the chip-free HLO
    budget test (tests/test_step_hlo_budget.py) relies on."""
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    h = flat.astype(cdt)
    out, off = [], 0
    for l in leaves:
        out.append(h[off:off + l.size].reshape(l.shape))
        off += l.size
    return out


def _downcast_group_fwd(leaves, cdt):
    return _downcast_group(leaves, cdt), None


def _downcast_group_bwd(cdt, _res, cots):
    # mirror of the forward: group the cdt->f32 gradient upcasts into one
    # convert (the cotangents carry the shapes, so no residuals needed)
    flat = jnp.concatenate([c.reshape(-1) for c in cots])
    f = flat.astype(jnp.float32)
    out, off = [], 0
    for c in cots:
        out.append(f[off:off + c.size].reshape(c.shape))
        off += c.size
    return (out,)


_downcast_group.defvjp(_downcast_group_fwd, _downcast_group_bwd)


def _flatten_state(state):
    """Eager create_state result -> fused state tuple (see the contract in
    Optimizer.fused_ops)."""
    if state is None:
        return ()
    if isinstance(state, tuple):
        return state
    return (state,)


class FusedStep:
    """One-program training step over a Module's bound executor.

    ``run(feed)`` consumes the executor's current arg/aux values plus the
    fused optimizer state, executes one compiled step, and returns
    ``(outputs, new_args, new_aux, new_opt)`` as jax values. The caller
    (Module) commits them.
    """

    def __init__(self, executor, optimizer, param_names, compute_dtype=None,
                 data_names=(), keep_f32=(), ddp_mesh=None, ddp_axis=None,
                 ddp_bucket_bytes=None):
        self._exec = executor
        self._opt = optimizer
        fused = optimizer.fused_ops()
        if fused is None:
            raise ValueError("optimizer %s has no fused form"
                             % type(optimizer).__name__)
        self._state_init, self._update = fused
        # only grad_req == 'write' params are updated; 'null' pass through
        self.param_names = [n for n in param_names
                            if executor._grad_req.get(n, "null") == "write"]
        self._name2idx = {n: i for i, n in enumerate(param_names)}
        self._compute_dtype = compute_dtype
        self._data_names = frozenset(data_names)
        # params that must NOT be downcast under mixed precision: BN
        # gamma/beta (their op consumes f32 natively — casting them would
        # just reintroduce per-layer converts at the op boundary)
        self._keep_f32 = frozenset(keep_f32)
        self._jitted = None
        # device-resident metric accumulation (device_metric.py): when
        # attached, the step threads a small (sum, count) carry and
        # updates it in-program — no per-batch host transfer
        self._met_fn = None
        # Bucketed data-parallel mode (parallel/ddp.py): the step is
        # shard_map'ped over `ddp_mesh`'s `ddp_axis` (batch args sharded,
        # everything else replicated) and the gradients pass through a
        # GradReducer — one fused lax.psum per size-bounded bucket, emitted
        # in reverse-production order so XLA can overlap the collectives
        # with the remaining backward compute.
        self._ddp_mesh = ddp_mesh
        self._reducer = None
        if ddp_mesh is not None:
            from ..parallel import ddp as _ddp
            self._ddp_axis = ddp_axis or _ddp.flags.ddp_axis
            # param order is forward/creation order, so the reducer's
            # reversed walk matches backward production order
            entries = [(k, tuple(executor.arg_dict[k].shape),
                        _np.dtype(executor.arg_dict[k].dtype))
                       for k in self.param_names]
            self._reducer = _ddp.GradReducer(
                entries, axis_name=self._ddp_axis,
                bucket_bytes=ddp_bucket_bytes, axis_size=ddp_mesh.size)
        self._build()

    # ------------------------------------------------------------------ build
    def _build(self):
        eval_fn = self._exec._eval_fn
        pnames = self.param_names
        update = self._update
        # Mixed precision (TPU analog of the reference's fp16 multi-
        # precision SGD, python/mxnet/optimizer/optimizer.py:452): master
        # weights and optimizer state stay f32; f32 params and data inputs
        # are cast to `compute_dtype` (bf16 on the MXU) INSIDE the
        # differentiated function, so gradients come back f32 and the
        # update applies to the f32 masters. Labels/loss heads stay f32.
        cdt = self._compute_dtype
        dnames = self._data_names
        keepf = self._keep_f32
        met_fn = self._met_fn
        reducer = self._reducer

        def step(params, rest, aux_vals, opt_state, met_state, lr_vec,
                 wd_vec, rescale, t, key):
            diff = params
            if cdt is not None:
                rest = {k: (v.astype(cdt)
                            if k in dnames and v.dtype == jnp.float32 else v)
                        for k, v in rest.items()}

            def f(d):
                if cdt is not None:
                    cast = [k for k, v in d.items()
                            if v.dtype == jnp.float32 and k not in keepf
                            and v.size > 0]
                    if cast:
                        low = _downcast_group([d[k] for k in cast], cdt)
                        d = dict(d)
                        d.update(zip(cast, low))
                return eval_fn({**rest, **d}, aux_vals, key, True)

            from ..executor import mirror_wrap
            outs, vjp, auxu = jax.vjp(mirror_wrap(f), diff, has_aux=True)
            # keep aux dtypes stable across steps (bf16 activations must
            # not flip the f32 BN accumulators and trigger a recompile)
            auxu = {k: v.astype(aux_vals[k].dtype) for k, v in auxu.items()}
            # all-ones cotangents: identical seed to Executor._fwd_bwd
            # (loss heads carry custom VJPs expecting it); dtype follows the
            # output (bf16 under mixed precision)
            ones = [jnp.ones(o.shape, o.dtype) for o in outs]
            grads = vjp(list(ones))[0]
            if reducer is not None:
                # bucketed cross-replica sum BEFORE the optimizer update —
                # every rank then applies the identical aggregated gradient
                # (the ps-lite server aggregation, collapsed into the step).
                # Each psum depends only on its own bucket's grads, so the
                # scheduler may hoist it over the rest of the backward.
                grads = reducer.reduce(grads)
            new_params = {}
            new_opt = {}
            for i, k in enumerate(pnames):
                nw, ns = update(params[k], grads[k], opt_state[k],
                                lr_vec[i], wd_vec[i], rescale, t)
                new_params[k] = nw.astype(params[k].dtype)
                new_opt[k] = ns
            new_aux = {**aux_vals, **auxu}
            # metric carry update happens in the SAME program, over the
            # traced outputs/labels — no host round-trip. met_state=None
            # (a leafless pytree, resolved at trace time) skips it, so
            # the public forward_backward path never accumulates.
            new_met = met_state
            if met_fn is not None and met_state is not None:
                new_met = met_fn(met_state, outs, rest)
            return outs, new_params, new_aux, new_opt, new_met

        # Shardings are not pinned here: the executor commits params/aux/
        # data to their mesh shardings (dp-sharded batch, replicated
        # weights) and init_state commits the optimizer state, so GSPMD
        # propagates from the committed inputs — including the gradient
        # psum over 'dp'.
        #
        # Two compiled variants of the SAME step:
        # * `_jitted` — no donation; backs the public forward_backward()/
        #   update() pair, whose contract allows reading the OLD params
        #   between the two calls (and skipping update() entirely);
        # * `_jitted_donate` — params/aux/opt-state donated, so XLA updates
        #   them in place instead of double-buffering ~2x the model size in
        #   HBM every step. Backs the atomic fit-loop step
        #   (Module._fit_step), which commits results immediately. Data/
        #   label inputs (`rest`) are never donated: callers legitimately
        #   reuse device-resident batches across steps.
        # jax.jit compiles lazily, so a fit()-only run pays for exactly one
        # compilation.
        if self._ddp_mesh is not None:
            sharded = self._ddp_shard(step)
            self._jitted = jax.jit(sharded)
            self._jitted_donate = jax.jit(sharded,
                                          donate_argnums=(0, 2, 3, 4))
        else:
            self._jitted = jax.jit(step)
            self._jitted_donate = jax.jit(step, donate_argnums=(0, 2, 3, 4))

        # K steps per dispatch: the classic TPU train-loop-under-scan.
        # One host->device dispatch executes K full steps over K stacked
        # batches, amortising the per-dispatch host/PJRT latency (dominant
        # behind a remote/tunneled chip, still measurable on a local one).
        # lr/wd enter once per dispatch; the update count t advances in the
        # scan carry so t-dependent optimizers (adam bias correction,
        # schedules consumed via t) stay exact. Retraces automatically when
        # K (the stacked leading dim) changes.
        def k_step(params, static_rest, aux_vals, opt_state, met_state,
                   feeds, lr_vec, wd_vec, rescale, t0, keys):
            def body(carry, xs):
                p, a, o, m, t = carry
                feed, key = xs
                outs, p2, a2, o2, m2 = step(p, {**static_rest, **feed},
                                            a, o, m, lr_vec, wd_vec,
                                            rescale, t, key)
                return (p2, a2, o2, m2, t + jnp.int32(1)), outs

            (p, a, o, m, _), outs = jax.lax.scan(
                body, (params, aux_vals, opt_state, met_state,
                       jnp.int32(t0)),
                (feeds, keys))
            return outs, p, a, o, m

        if self._ddp_mesh is not None:
            # the K-step in_specs depend on which args arrive stacked as
            # feeds (run_k's split), so the shard_map is built lazily per
            # feed-name set (stable across a fit run -> one jit cache hit)
            self._k_fn = k_step
            self._k_cache = {}
            self._jitted_k = None
        else:
            self._jitted_k = jax.jit(k_step, donate_argnums=(0, 2, 3, 4))

    # -------------------------------------------------------------------- ddp
    def _ddp_spec(self, name):
        """Input spec for one executor arg: batch args shard over the dp
        axis, everything else is replicated."""
        from jax.sharding import PartitionSpec as P
        return (P(self._ddp_axis) if name in self._exec._batch_args
                else P())

    def _ddp_shard(self, step):
        """shard_map the per-step fn over the dp mesh: params/aux/opt/
        hypers replicated, batch args sharded, outputs batch-sharded.
        check_rep=False because the replication of the updated params is
        established by construction (identical update from the psum'd
        gradient on every rank), which the checker cannot prove."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        pset = set(self.param_names)
        rest_spec = {k: self._ddp_spec(k) for k in self._exec.arg_dict
                     if k not in pset}
        in_specs = (P(), rest_spec, P(), P(), P(), P(), P(), P(), P(), P())
        out_specs = (P(self._ddp_axis), P(), P(), P(), P())
        return shard_map(step, mesh=self._ddp_mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    def _ddp_jitted_k(self, feed_names):
        """The K-step variant of :meth:`_ddp_shard`, cached per feed-name
        set; feeds are stacked (K, batch, ...) so their batch axis is
        dim 1 (spec ``P(None, dp)``)."""
        key = frozenset(feed_names)
        fn = self._k_cache.get(key)
        if fn is None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            ax = self._ddp_axis
            pset = set(self.param_names)
            static_spec = {k: self._ddp_spec(k) for k in self._exec.arg_dict
                           if k not in pset and k not in key}
            feed_spec = {k: (P(None, ax) if k in self._exec._batch_args
                             else P()) for k in key}
            in_specs = (P(), static_spec, P(), P(), P(), feed_spec,
                        P(), P(), P(), P(), P())
            out_specs = (P(None, ax), P(), P(), P(), P())
            fn = jax.jit(
                shard_map(self._k_fn, mesh=self._ddp_mesh,
                          in_specs=in_specs, out_specs=out_specs,
                          check_rep=False),
                donate_argnums=(0, 2, 3, 4))
            self._k_cache[key] = fn
        return fn

    def _ddp_globalize(self, tree, spec):
        """Promote every leaf of ``tree`` to a global array on the dp mesh
        (no-op for leaves already there — params/opt state after step 1)."""
        from ..parallel import ddp as _ddp
        return jax.tree_util.tree_map(
            lambda v: _ddp.to_global(v, self._ddp_mesh, spec), tree)

    def ddp_stats(self):
        """Host-held bucket/comm summary (telemetry source), or None when
        the step is not in DDP mode."""
        return self._reducer.stats() if self._reducer is not None else None

    # ----------------------------------------------------------------- metric
    def attach_metric(self, met_fn):
        """Fold a device metric update into the step: ``met_fn(state,
        outs, rest) -> new_state`` (pure, traced). Rebuilds the jitted
        wrappers; compilation is lazy, so attaching before the first
        dispatch costs nothing extra."""
        if self._met_fn is met_fn:
            return
        if self._ddp_mesh is not None:
            # the metric carry is replicated (out spec P()) but would
            # accumulate per-rank LOCAL batches under check_rep=False —
            # silently wrong. Module keeps the host metric path in DDP
            # mode; fail loudly if something routes around that guard.
            raise ValueError("device metrics cannot fold into a DDP step; "
                             "keep the host metric path (MXNET_DDP)")
        self._met_fn = met_fn
        self._build()

    def detach_metric(self):
        if self._met_fn is None:
            return
        self._met_fn = None
        self._build()

    # ------------------------------------------------------------------- state
    def init_state(self):
        """Fused optimizer state from the executor's current params, placed
        like the params (replicated on the mesh when SPMD)."""
        opt = {}
        ex = self._exec
        for k in self.param_names:
            w = ex.arg_dict[k]._data
            st = self._state_init(w)
            if ex._mesh is not None:
                st = tuple(jax.device_put(s, ex._rep_sharding) for s in st)
            opt[k] = st
        return opt

    def state_from_updater(self, updater_states):
        """Adopt eager Updater states {idx: create_state result} (e.g. after
        load_optimizer_states) into the fused layout."""
        opt = {}
        for k in self.param_names:
            idx = self._name2idx[k]
            if idx in updater_states:
                opt[k] = tuple(
                    s._data for s in _flatten_state(updater_states[idx]))
            else:
                opt[k] = self._state_init(self._exec.arg_dict[k]._data)
        return opt

    def state_to_updater(self, opt_state):
        """Fused state -> eager Updater layout, so save_optimizer_states
        round-trips regardless of which path trained."""
        from ..ndarray.ndarray import NDArray
        out = {}
        for k, st in opt_state.items():
            idx = self._name2idx[k]
            arrs = tuple(NDArray(s) for s in st)
            if len(arrs) == 0:
                out[idx] = None
            elif len(arrs) == 1:
                out[idx] = arrs[0]
            else:
                out[idx] = arrs
        return out

    # --------------------------------------------------------------------- run
    def hyper_peek(self):
        """Per-step dynamic hyperparameters AS IF the update counts had been
        bumped (the eager Updater bumps inside optimizer.update). The actual
        bump is deferred to :meth:`commit_counts` — called from
        Module.update() — so a step whose update() is skipped leaves the
        optimizer bookkeeping untouched, exactly like the eager path."""
        opt = self._opt
        idxs = [self._name2idx[k] for k in self.param_names]
        peek = {i: opt._index_update_count.get(i, opt.begin_num_update) + 1
                for i in idxs}
        num_update = max([opt.num_update] + list(peek.values()))
        lr_vec = [opt._get_lr(i, num_update=num_update) for i in idxs]
        wd_vec = [opt._get_wd(i) for i in idxs]
        t = _np.int32(peek[idxs[0]]) if idxs else _np.int32(num_update)
        return (_np.asarray(lr_vec, _np.float32),
                _np.asarray(wd_vec, _np.float32),
                _np.float32(opt.rescale_grad), t)

    def commit_counts(self):
        """The eager bookkeeping hyper_peek() previewed: bump each param's
        update count (advancing num_update / the LR schedule)."""
        for k in self.param_names:
            self._opt._update_count(self._name2idx[k])

    def split_args(self, arg_vals):
        """Split a full executor arg dict into (updated params, the rest)."""
        params = {k: arg_vals[k] for k in self.param_names}
        rest = {k: v for k, v in arg_vals.items() if k not in params}
        return params, rest

    def run(self, arg_vals, aux_vals, opt_state, key, donate=False,
            met_state=None):
        """One fused step. With ``donate=True`` the param/aux/opt-state
        (and metric-carry) buffers are DONATED to XLA (updated in place);
        the caller must commit the returned values immediately — the
        inputs are dead."""
        lr_vec, wd_vec, rescale, t = self.hyper_peek()
        params, rest = self.split_args(arg_vals)
        fn = self._jitted_donate if donate else self._jitted
        if self._ddp_mesh is not None:
            from jax.sharding import PartitionSpec as P
            from ..parallel import ddp as _ddp
            mesh = self._ddp_mesh
            # every array input must be a global array on the dp mesh
            # (mixing process-local and global arrays in one multi-host
            # jit is an error); hypers stay host numpy == replicated
            params = self._ddp_globalize(params, P())
            aux_vals = self._ddp_globalize(aux_vals, P())
            opt_state = self._ddp_globalize(opt_state, P())
            rest = {k: _ddp.to_global(v, mesh, self._ddp_spec(k))
                    for k, v in rest.items()}
            key = _ddp.to_global(key, mesh, P())
        else:
            lr_vec, wd_vec = jnp.asarray(lr_vec), jnp.asarray(wd_vec)
        outs, new_params, new_aux, new_opt, new_met = fn(
            params, rest, aux_vals, opt_state, met_state,
            lr_vec, wd_vec, rescale, t, key)
        if self._ddp_mesh is not None:
            # outputs are global batch-sharded; hand the commit/metric
            # path this rank's local view (reference per-worker semantics)
            outs = jax.tree_util.tree_map(
                lambda o: _ddp.from_global(o, self._ddp_mesh,
                                           P(self._ddp_axis)),
                outs)
        new_args = dict(rest)
        new_args.update(new_params)
        return outs, new_args, new_aux, new_opt, new_met

    def stack_feeds(self, feeds):
        """Cast + stack K per-step ``{input_name: jax value}`` feeds into
        the ``(K, ...)`` device layout ``k_step`` scans over. Factored out
        of :meth:`run_k` so the staged device feed
        (mxnet_tpu/data/feed.py) can commit the NEXT window's buffer while
        the current dispatch is still in flight; both paths run exactly
        these ops in this order, so staged and unstaged windows are
        bitwise-identical."""
        ex = self._exec
        cdt = self._compute_dtype
        stacked = {}
        for name in feeds[0]:
            vals = [f[name] for f in feeds]
            if cdt is not None and name in self._data_names \
                    and vals[0].dtype == jnp.float32:
                # the step would cast each slice anyway; casting before the
                # stack halves the stacked buffer
                vals = [v.astype(cdt) for v in vals]
            arr = jnp.stack(vals)
            if ex._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                spec = P(None, "dp") if name in ex._batch_args else P()
                arr = jax.device_put(arr, NamedSharding(ex._mesh, spec))
            stacked[name] = arr
        return stacked

    def run_k(self, arg_vals, aux_vals, opt_state, feeds, keys,
              met_state=None):
        """K fused steps in ONE XLA program (`lax.scan` over stacked
        batches) — see ``k_step`` in :meth:`_build`.

        ``feeds`` is a list of K ``{input_name: jax value}`` dicts (the
        per-step data/label feeds), or ONE already-stacked
        ``{input_name: (K, ...) array}`` dict from :meth:`stack_feeds`
        (the staged device feed pre-commits it so dispatch never waits on
        the H2D); ``keys`` a list of K PRNG keys. The param/aux/opt-state
        (and metric-carry) buffers are DONATED; the caller must commit
        the returned values immediately. Returns
        ``(outs, new_params, new_aux, new_opt, new_met)`` where each
        element of ``outs`` is stacked ``(K, ...)`` so callers can still
        update metrics per sub-batch.

        lr/wd are evaluated once per dispatch (a schedule moves in steps of
        K); the optimizer update count still advances per inner step.
        """
        lr_vec, wd_vec, rescale, t = self.hyper_peek()
        params, rest = self.split_args(arg_vals)
        if isinstance(feeds, dict):
            stacked = feeds
        else:
            stacked = self.stack_feeds(feeds)
        feed_names = frozenset(stacked)
        static_rest = {k: v for k, v in rest.items() if k not in feed_names}
        ex = self._exec
        if self._ddp_mesh is not None:
            from jax.sharding import PartitionSpec as P
            from ..parallel import ddp as _ddp
            mesh, ax = self._ddp_mesh, self._ddp_axis
            params = self._ddp_globalize(params, P())
            aux_vals = self._ddp_globalize(aux_vals, P())
            opt_state = self._ddp_globalize(opt_state, P())
            static_rest = {k: _ddp.to_global(v, mesh, self._ddp_spec(k))
                           for k, v in static_rest.items()}
            stacked = {k: _ddp.to_global(
                           v, mesh,
                           P(None, ax) if k in ex._batch_args else P())
                       for k, v in stacked.items()}
            kk = _ddp.to_global(jnp.stack(list(keys)), mesh, P())
            outs, new_params, new_aux, new_opt, new_met = \
                self._ddp_jitted_k(stacked)(
                    params, static_rest, aux_vals, opt_state, met_state,
                    stacked, lr_vec, wd_vec, rescale, t, kk)
            outs = jax.tree_util.tree_map(
                lambda o: _ddp.from_global(o, mesh, P(None, ax)), outs)
            return outs, new_params, new_aux, new_opt, new_met
        outs, new_params, new_aux, new_opt, new_met = self._jitted_k(
            params, static_rest, aux_vals, opt_state, met_state, stacked,
            jnp.asarray(lr_vec), jnp.asarray(wd_vec), rescale, t,
            jnp.stack(list(keys)))
        return outs, new_params, new_aux, new_opt, new_met

    def cost_analysis(self, arg_vals, aux_vals, opt_state):
        """XLA cost analysis of the compiled fused step (flops etc.), via
        AOT lowering with the current executor values as abstract inputs.
        Returns the cost dict or None."""
        npar = len(self.param_names)
        params, rest = self.split_args(arg_vals)
        lowered = self._jitted.lower(
            params, rest, aux_vals, opt_state, None,
            jnp.zeros((npar,), jnp.float32), jnp.zeros((npar,), jnp.float32),
            _np.float32(1.0), _np.int32(1), jax.random.PRNGKey(0))
        try:
            # pre-compile HLO-level analysis: avoids a second (multi-minute
            # over the remote-compile tunnel) XLA compilation just for flops
            cost = lowered.cost_analysis()
        except Exception:
            cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        return cost
