"""Graph rewrite: eligible f32 FC/Conv sites -> int8 serving ops.

The contrib ``quantize_graph`` mold (old-node -> new-node mapping over
the topo order), but targeting the TPP-style closed primitive pair in
:mod:`ops/quant_serve`: every quantized site becomes ONE node —
static-scale int8 quantize, int8 dot/conv with int32 accumulate, and a
fused dequant epilogue that already carries the inference BatchNorm
affine and a trailing ReLU. Weights are quantized HERE, host-side, into
new int8 parameter arrays (symmetric per-output-channel), so the
exported artifact bakes int8 constants and the f32 weights disappear
from the checkpoint entirely — that is the 4x payload cut.

Fold math (all float32 numpy, deterministic):

    Wq[k]        = clip(round(W[k] * w_scale[k]), +-127)   int8
    deq[k]       = 1 / (act_scale * w_scale[k])
    BN inference:  a[k] = gamma[k]/sqrt(var[k]+eps),
                   c[k] = beta[k] - mean[k]*a[k]   (gamma=1 if fix_gamma)
    out_scale[k] = deq[k] * a[k]
    out_bias[k]  = a[k] * bias[k] + c[k]

so ``act(acc*out_scale + out_bias)`` equals BN(ReLU-free site + bias)
up to int8 rounding. Sites that fail any guard keep their f32 node and
are listed in the report with the reason.
"""
from __future__ import annotations

import numpy as _np

from ..ops import registry as _registry
from ..symbol.symbol import Node, Symbol

__all__ = ["quantize_serving_graph"]

_EPS = 1e-8


def _np32(v):
    v = v.asnumpy() if hasattr(v, "asnumpy") else v
    return _np.asarray(v, _np.float32)


def _consumers(sym):
    out = {}
    for node in sym._topo():
        if node.is_variable:
            continue
        for (src, _oi) in node.inputs:
            out.setdefault(id(src), []).append(node)
    return out


def _sole_consumer(node, consumers):
    cs = consumers.get(id(node), [])
    return cs[0] if len(cs) == 1 else None


def _bn_inputs(bn, arg_params, aux_params):
    """(gamma, beta, mean, var) names when every BN input is a direct
    checkpoint Variable; None otherwise."""
    names = []
    for i, store in ((1, arg_params), (2, arg_params), (3, aux_params),
                     (4, aux_params)):
        if i >= len(bn.inputs):
            return None
        src, _ = bn.inputs[i]
        if not src.is_variable or src.name not in store:
            return None
        names.append(src.name)
    return names


def _fold_chain(site, consumers, output_ids, arg_params, aux_params):
    """Absorbable (bn_node, relu_node) following ``site`` — either may be
    None. Interior absorbed nodes must have exactly one consumer and must
    not themselves be graph outputs."""
    bn = relu = None
    c = _sole_consumer(site.node, consumers)
    if (c is not None and not c.is_variable and c.op.name == "BatchNorm"
            and id(site.node) not in output_ids
            and c.inputs[0][0] is site.node
            and int(c.params.get("axis", 1)) == 1
            and not c.params.get("output_mean_var", False)
            and _bn_inputs(c, arg_params, aux_params) is not None):
        bn = c
    tail = bn if bn is not None else site.node
    c = _sole_consumer(tail, consumers)
    if (c is not None and not c.is_variable and c.op.name == "Activation"
            and c.params.get("act_type", "relu") == "relu"
            and id(tail) not in output_ids and c.inputs[0][0] is tail):
        if bn is not None or tail is site.node:
            relu = c
    return bn, relu


def _var(name, shape, dtype):
    return Node(None, name, [], {},
                {"__shape__": tuple(shape), "__dtype__": str(dtype)})


def quantize_serving_graph(sym, arg_params, aux_params, calib):
    """Rewrite ``sym`` using a :class:`~.calibrate.CalibrationResult`.

    Returns ``(qsym, qarg_params, qaux_params, report)``. Parameters of
    quantized sites are REPLACED (f32 weight/bias/BN params dropped, int8
    weight + f32 epilogue scale/bias added); untouched parameters pass
    through so mixed graphs keep working.
    """
    consumers = _consumers(sym)
    output_ids = {id(n) for n, _ in sym._entries}
    by_name = {s.name: s for s in calib.sites}
    skipped = dict(calib.skipped)
    new_params = {}
    mapping = {}
    absorbed = {}         # id(absorbed bn/relu node) -> fused Node
    quantized = []
    f32_weight_bytes = 0
    int8_weight_bytes = 0

    def mapped_entry(entry):
        node, idx = entry
        m = mapping[id(node)]
        return (m, 0) if id(node) in absorbed else (m, idx)

    for node in sym._topo():
        if node.is_variable:
            mapping[id(node)] = node
            continue
        if id(node) in absorbed:
            mapping[id(node)] = absorbed[id(node)]
            continue
        site = by_name.get(node.name) if node.name in by_name else None
        if site is not None and site.node is node:
            bn, relu = _fold_chain(site, consumers, output_ids,
                                   arg_params, aux_params)
            act = "relu" if relu is not None else "identity"
            w = _np32(arg_params[site.weight_name])
            w_scale = calib.weight_scale[site.name]        # (K,) f32
            act_scale = _np.float32(calib.act_scale[site.name])
            bshape = (-1,) + (1,) * (w.ndim - 1)
            wq = _np.clip(_np.round(w * w_scale.reshape(bshape)),
                          -127, 127).astype(_np.int8)
            deq = (_np.float32(1.0)
                   / (act_scale * w_scale)).astype(_np.float32)
            bias = (_np32(arg_params[site.bias_name])
                    if site.bias_name else _np.zeros(w.shape[0],
                                                     _np.float32))
            if bn is not None:
                gname, bname, mname, vname = _bn_inputs(
                    bn, arg_params, aux_params)
                eps = _np.float32(bn.params.get("eps", 1e-3))
                gamma = (_np.ones(w.shape[0], _np.float32)
                         if bn.params.get("fix_gamma", True)
                         else _np32(arg_params[gname]))
                beta = _np32(arg_params[bname])
                mean = _np32(aux_params[mname])
                var = _np32(aux_params[vname])
                a = (gamma / _np.sqrt(var + eps)).astype(_np.float32)
                c = (beta - mean * a).astype(_np.float32)
            else:
                a = _np.ones(w.shape[0], _np.float32)
                c = _np.zeros(w.shape[0], _np.float32)
            out_scale = (deq * a).astype(_np.float32)
            out_bias = (a * bias + c).astype(_np.float32)

            wq_v = _var(site.name + "_weight_q", wq.shape, "int8")
            sc_v = _var(site.name + "_oscale", out_scale.shape, "float32")
            ob_v = _var(site.name + "_obias", out_bias.shape, "float32")
            new_params[site.name + "_weight_q"] = wq
            new_params[site.name + "_oscale"] = out_scale
            new_params[site.name + "_obias"] = out_bias
            f32_weight_bytes += w.nbytes + bias.nbytes
            int8_weight_bytes += (wq.nbytes + out_scale.nbytes
                                  + out_bias.nbytes)
            data_e = mapped_entry(node.inputs[0])
            if site.kind == "conv":
                qop = _registry.get("_contrib_quantized_conv_int8")
                params = {"kernel": tuple(node.params["kernel"]),
                          "num_filter": node.params["num_filter"],
                          "stride": node.params.get("stride"),
                          "dilate": node.params.get("dilate"),
                          "pad": node.params.get("pad"),
                          "act_scale": float(act_scale), "act": act}
            else:
                qop = _registry.get("_contrib_quantized_fc_int8")
                params = {"num_hidden": node.params.get(
                              "num_hidden", w.shape[0]),
                          "flatten": node.params.get("flatten", True),
                          "act_scale": float(act_scale), "act": act}
            qnode = Node(qop, site.name + "_int8",
                         [data_e, (wq_v, 0), (sc_v, 0), (ob_v, 0)],
                         params)
            mapping[id(node)] = qnode
            for absorbed_node in (bn, relu):
                if absorbed_node is not None:
                    absorbed[id(absorbed_node)] = qnode
            quantized.append(site.name)
        else:
            new_inputs = [mapped_entry(e) for e in node.inputs]
            mapping[id(node)] = Node(node.op, node.name, new_inputs,
                                     dict(node.params), dict(node.attrs))

    qsym = Symbol([mapped_entry((n, i)) for n, i in sym._entries])
    keep_args = set(qsym.list_arguments())
    keep_aux = set(qsym.list_auxiliary_states())
    qargs = {k: v for k, v in arg_params.items() if k in keep_args}
    qargs.update({k: v for k, v in new_params.items() if k in keep_args})
    qaux = {k: v for k, v in aux_params.items() if k in keep_aux}
    report = {
        "scheme": "int8-symmetric/per-channel-weight/per-tensor-act",
        "sites": list(quantized),
        "skipped": dict(skipped),
        "calibration": calib.to_dict(),
        "weight_bytes": {"f32": int(f32_weight_bytes),
                         "int8": int(int8_weight_bytes)},
    }
    return qsym, qargs, qaux, report
