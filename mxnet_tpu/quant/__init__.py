"""Post-training int8 quantization for the serving path (ROADMAP item 5).

Pipeline (PAPER.md capability 7, TPP-style closed primitive set):

1. :func:`calibrate` — one traced forward per calibration batch over the
   eligible FullyConnected/Convolution sites' data inputs, activation
   amax accumulated as a DONATED device carry (the PR-3 device-metric
   discipline), ONE batched device->host fetch at the very end.
   Per-output-channel weight ranges come host-side from the checkpoint.
2. :func:`quantize_serving_graph` — rewrite eligible sites onto the two
   serving ops in :mod:`ops/quant_serve` (static-scale int8 quantize ->
   int8 dot/conv with int32 accumulate -> fused dequant epilogue through
   the kernel tier), folding the inference BatchNorm affine and a
   trailing ReLU into the epilogue. Strict eligibility guards; every
   "no" keeps the f32 node and is reported with its reason.
3. :func:`export_quantized` — emit a ``format_version`` 4 ``.mxtpu``
   artifact (int8 weight constants baked into the StableHLO, ~4x
   smaller weight payload) that ``load_artifact`` / the serve engine
   cache treat as a first-class predict artifact with dtype "int8".

CLI: ``tools/quantize_model.py``. Docs: docs/quantization.md.
"""
from .calibrate import CalibrationResult, calibrate, find_sites
from .rewrite import quantize_serving_graph

__all__ = ["CalibrationResult", "calibrate", "find_sites",
           "quantize_serving_graph", "quantize_serving_model",
           "export_quantized"]


def quantize_serving_model(sym, arg_params, aux_params, calib_batches,
                           data_names=("data",), excluded=(),
                           num_calib_examples=None):
    """Calibrate + rewrite in one call.

    ``calib_batches``: iterable of dict name -> array (host or device).
    Returns ``(qsym, qarg_params, qaux_params, report)`` where report is
    the JSON-able ``quant`` record the artifact metadata carries.
    """
    calib = calibrate(sym, arg_params, aux_params, calib_batches,
                      data_names=data_names, excluded=excluded,
                      num_calib_examples=num_calib_examples)
    return quantize_serving_graph(sym, arg_params, aux_params, calib)


def export_quantized(sym, arg_params, aux_params, calib_batches,
                     data_shapes, path, data_names=None, excluded=(),
                     num_calib_examples=None, dtype="float32",
                     platforms=None, dynamic_batch=False):
    """Quantize and freeze into a ``format_version`` 4 artifact at
    ``path``; returns the artifact metadata (with the ``quant`` record).
    """
    from .. import serving as _serving
    if data_names is None:
        data_names = tuple(data_shapes)
    qsym, qargs, qaux, report = quantize_serving_model(
        sym, arg_params, aux_params, calib_batches,
        data_names=data_names, excluded=excluded,
        num_calib_examples=num_calib_examples)
    return _serving.export_compiled(
        qsym, qargs, qaux, data_shapes, path, dtype=dtype,
        platforms=platforms, dynamic_batch=dynamic_batch,
        format_version=4, extra_meta={"quant": report})
