"""Calibration pass: activation + weight ranges for int8 quantization.

Host-sync discipline (PR 3 / MXL101): the per-site activation amax
vector lives ON DEVICE as a tiny f32 carry, donated back into the jitted
step every batch (``jnp.maximum`` fold — order-independent, so the
result is bitwise identical across runs and across
``MXNET_ENGINE_DEPTH`` settings), and is fetched with ONE
``jax.device_get`` after the last batch. The legacy
``contrib/quantization`` calibrator fetches every probed tensor every
batch; this one adds exactly one device->host transfer total, pinned by
tests/test_quant.py per the test_step_sync_budget.py conventions.

Per-output-channel weight ranges never touch the device at all: they
are exact maxima over checkpoint arrays, computed host-side in numpy.
"""
from __future__ import annotations

import hashlib

import numpy as _np

from ..base import MXNetError

__all__ = ["SiteInfo", "CalibrationResult", "find_sites", "calibrate"]

_EPS = 1e-8
_QUANTIZABLE = ("FullyConnected", "Convolution")


class SiteInfo:
    """One eligible FullyConnected/Convolution site."""

    __slots__ = ("name", "kind", "node", "weight_name", "bias_name")

    def __init__(self, name, kind, node, weight_name, bias_name):
        self.name = name
        self.kind = kind            # "fc" | "conv"
        self.node = node
        self.weight_name = weight_name
        self.bias_name = bias_name  # None when no_bias


def _entry_var(entry):
    node, _ = entry
    return node.name if node.is_variable else None


def _host(v):
    """Checkpoint param as host numpy, WITHOUT touching the profiler's
    sync counters: weight-range math is checkpoint-domain preprocessing,
    not part of the device calibration loop the one-d2h budget pins
    (``NDArray.asnumpy`` would record a d2h per weight)."""
    if hasattr(v, "_data"):
        v = v._data
    elif hasattr(v, "asnumpy"):
        return v.asnumpy()
    return _np.asarray(v)


def find_sites(sym, arg_params, excluded=()):
    """(eligible sites in topo order, {name: reason} for the skipped).

    Strict guards — a site quantizes only when the int8 op pair can
    reproduce it exactly: direct f32 weight Variable present in the
    checkpoint, groups=1 / default-layout NCHW for conv, direct bias
    Variable when biased. Everything else stays f32, with the reason
    recorded for the report.
    """
    excluded = set(excluded)
    sites, skipped = [], {}
    for node in sym._topo():
        if node.is_variable or node.op.name not in _QUANTIZABLE:
            continue
        name = node.name
        if name in excluded:
            skipped[name] = "excluded by caller"
            continue
        wname = _entry_var(node.inputs[1]) if len(node.inputs) > 1 else None
        if wname is None or wname not in arg_params:
            skipped[name] = "weight is not a direct checkpoint Variable"
            continue
        w = _host(arg_params[wname])
        if w.dtype != _np.float32:
            skipped[name] = "weight dtype %s is not float32" % w.dtype
            continue
        no_bias = bool(node.params.get("no_bias", False))
        bname = None
        if not no_bias and len(node.inputs) > 2:
            bname = _entry_var(node.inputs[2])
            if bname is None or bname not in arg_params:
                skipped[name] = "bias is not a direct checkpoint Variable"
                continue
        if node.op.name == "Convolution":
            if int(node.params.get("num_group", 1) or 1) != 1:
                skipped[name] = "grouped convolution (num_group != 1)"
                continue
            if node.params.get("layout") not in (None, "NCHW"):
                skipped[name] = ("layout %r is not NCHW"
                                 % node.params.get("layout"))
                continue
            if len(tuple(node.params.get("kernel", ()))) != 2 or w.ndim != 4:
                skipped[name] = "only 2-D NCHW convolutions quantize"
                continue
            kind = "conv"
        else:
            if w.ndim != 2:
                skipped[name] = "FC weight is not 2-D"
                continue
            kind = "fc"
        sites.append(SiteInfo(name, kind, node, wname, bname))
    return sites, skipped


class CalibrationResult:
    """Ranges + scales for the eligible sites.

    * ``act_amax[name]`` — per-tensor |max| of the site's f32 input.
    * ``act_scale[name]`` — 127 / amax (the static quantize multiplier).
    * ``weight_amax[name]`` / ``weight_scale[name]`` — per-output-channel
      f32 vectors.
    """

    def __init__(self, sites, skipped, act_amax, weight_amax, batches,
                 examples):
        self.sites = sites
        self.skipped = dict(skipped)
        self.act_amax = dict(act_amax)
        self.weight_amax = dict(weight_amax)
        self.batches = batches
        self.examples = examples
        self.act_scale = {
            n: float(_np.float32(127.0)
                     / _np.maximum(_np.float32(a), _np.float32(_EPS)))
            for n, a in self.act_amax.items()}
        self.weight_scale = {
            n: (_np.float32(127.0)
                / _np.maximum(a.astype(_np.float32), _np.float32(_EPS)))
            for n, a in self.weight_amax.items()}

    def fingerprint(self):
        """sha256 over every scale, bit-exact — the calibration
        determinism witness (same data + seed -> same fingerprint)."""
        h = hashlib.sha256()
        for name in sorted(self.act_scale):
            h.update(name.encode())
            h.update(_np.float32(self.act_scale[name]).tobytes())
        for name in sorted(self.weight_scale):
            h.update(name.encode())
            h.update(self.weight_scale[name].tobytes())
        return h.hexdigest()

    def to_dict(self):
        return {
            "batches": self.batches,
            "examples": self.examples,
            "fingerprint": self.fingerprint(),
            "act_amax": {n: float(a) for n, a in
                         sorted(self.act_amax.items())},
            "skipped": dict(self.skipped),
        }


def _raw(v):
    if hasattr(v, "_data"):
        return v._data
    if hasattr(v, "asnumpy"):
        return v.asnumpy()
    return v


def calibrate(sym, arg_params, aux_params, batches, data_names=("data",),
              excluded=(), num_calib_examples=None):
    """Collect calibration ranges over ``batches`` (iterable of dict
    name -> array). Exactly ONE device->host fetch total."""
    import jax
    import jax.numpy as jnp
    from .. import profiler
    from ..executor import _graph_eval_fn
    from ..symbol.symbol import Symbol

    sites, skipped = find_sites(sym, arg_params, excluded=excluded)
    if not sites:
        raise MXNetError(
            "quant.calibrate: no eligible FullyConnected/Convolution "
            "sites (skipped: %s)" % (skipped or "none found"))
    # probe symbol over each site's DATA input (contrib calibrator idiom)
    probe = Symbol([s.node.inputs[0] for s in sites])
    eval_fn = _graph_eval_fn(probe)
    key = jax.random.PRNGKey(0)
    arg_vals = {k: jnp.asarray(_raw(v)) for k, v in arg_params.items()}
    aux_vals = {k: jnp.asarray(_raw(v)) for k, v in aux_params.items()}

    def step(carry, data_vals):
        vals = dict(arg_vals)
        vals.update(data_vals)
        outs, _ = eval_fn(vals, aux_vals, key, False)
        amax = jnp.stack([jnp.max(jnp.abs(o)).astype(jnp.float32)
                          for o in outs])
        return jnp.maximum(carry, amax)

    jitted = jax.jit(step, donate_argnums=(0,))
    carry = jnp.zeros((len(sites),), jnp.float32)
    n_batches = examples = 0
    for batch in batches:
        if not isinstance(batch, dict):
            # single-input convenience: a bare array per batch
            if len(data_names) != 1:
                raise MXNetError(
                    "quant.calibrate: batches must be dicts name -> array "
                    "when the model has %d data inputs %r"
                    % (len(data_names), tuple(data_names)))
            batch = {data_names[0]: batch}
        data_vals = {n: jnp.asarray(_raw(batch[n])) for n in data_names}
        carry = jitted(carry, data_vals)
        n_batches += 1
        examples += int(data_vals[data_names[0]].shape[0])
        if num_calib_examples is not None and examples >= num_calib_examples:
            break
    if n_batches == 0:
        raise MXNetError("quant.calibrate: empty calibration set")
    # THE one batched d2h of the whole pass
    host = _np.asarray(jax.device_get(carry), _np.float32)
    profiler.record_host_sync("d2h", host.nbytes)
    act_amax = {s.name: float(host[i]) for i, s in enumerate(sites)}
    zero = [n for n, a in act_amax.items() if a <= 0.0]
    for n in zero:
        skipped[n] = "zero activation range over the calibration set"
        del act_amax[n]
    sites = [s for s in sites if s.name in act_amax]
    # per-output-channel weight ranges: exact, host-side, no device work
    weight_amax = {}
    for s in sites:
        w = _np.asarray(_host(arg_params[s.weight_name]), _np.float32)
        red = tuple(range(1, w.ndim))
        weight_amax[s.name] = _np.abs(w).max(axis=red).astype(_np.float32)
    return CalibrationResult(sites, skipped, act_amax, weight_amax,
                             n_batches, examples)
