"""Optimizer update operators.

Parity: src/operator/optimizer_op.cc in the reference — updates are *ops* so
they run on-device and can be fused/jitted (the reference does this so the
kvstore server can apply them; we do it so XLA fuses update chains into the
training step). Each returns the new weight (+ new state), pure-functionally.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep(grad, rescale_grad, clip_gradient, wd, weight):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update")
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g_acc, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_acc
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("signsgd_update")
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("adagrad_update", num_outputs=2)
def adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_h = history + jnp.square(g)
    return weight - lr * g / jnp.sqrt(new_h + epsilon), new_h


@register("adadelta_update", num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, *, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, *, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z


# multi-precision (fp16 weights, fp32 master copy) — reference mp_sgd_update
@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=False):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd, weight32)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=False):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd, weight32)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("group_adagrad_update", num_outputs=2)
def group_adagrad_update(weight, grad, history, *, lr, epsilon=1e-5,
                         rescale_grad=1.0, clip_gradient=-1.0):
    """Group-sparsity AdaGrad (parity:
    src/operator/contrib/adgrad_update_op-inl.h:104-137): one shared
    accumulator per ROW — history[i] += mean(g[i]^2); w -= lr * g /
    sqrt(history + eps). The row mean keeps the accumulator scale
    independent of embedding width."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    axes = tuple(range(1, g.ndim))
    ssq = jnp.mean(jnp.square(g), axis=axes) if axes else jnp.square(g)
    # history is (N,) from the op path or (N, 1) from the python
    # optimizer's create_state (reference contrib.py:66 keepdims) —
    # preserve whichever layout came in
    new_hist = history + ssq.reshape(history.shape)
    bshape = weight.shape[:1] + (1,) * len(axes)
    new_w = weight - lr * g / jnp.sqrt(new_hist.reshape(bshape) + epsilon)
    return new_w, new_hist
