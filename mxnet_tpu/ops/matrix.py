"""Shape-manipulation, indexing, joining and linear-algebra ops.

Parity: src/operator/tensor/matrix_op.cc, dot-inl.h, indexing_op.cc,
ordering_op.cc, init_op.cc in the reference. All static-shape so XLA can tile
matmuls onto the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from ..base import index_dtype as _index_dtype


@register("Reshape")
def reshape(data, *, shape=None, reverse=False):
    """MXNet reshape with special codes 0 (copy dim), -1 (infer),
    -2 (copy rest), -3 (merge two), -4 (split, consumes two following)."""
    if shape is None:
        raise ValueError("reshape requires shape")
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = list(shape)[::-1]
    out = []
    i = 0  # index into src
    it = iter(range(len(shape)))
    shape = list(shape)
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s)
            if i < len(src):
                i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


alias("Reshape", "reshape")


@register("Flatten")
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


alias("Flatten", "flatten")


@register("transpose")
def transpose(data, *, axes=None):
    if axes is None or (hasattr(axes, "__len__") and len(axes) == 0):
        return jnp.transpose(data)
    return jnp.transpose(data, tuple(axes))


@register("expand_dims")
def expand_dims(data, *, axis):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis)


@register("slice")
def slice_op(data, *, begin, end, step=None):
    nd = data.ndim
    begin = list(begin) + [None] * (nd - len(begin))
    end = list(end) + [None] * (nd - len(end))
    step = list(step or []) + [None] * (nd - len(step or []))
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def slice_axis(data, *, axis, begin=0, end=None):
    # end=None slices to the end of the axis (reference slice_axis accepts
    # None for both bounds)
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, *, axes=None):
    axes = range(data.ndim) if axes is None or len(axes) == 0 else axes
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("Concat")
def concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


alias("Concat", "concat")


@register("stack")
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register("split", num_outputs=lambda p: int(p.get("num_outputs", 1)))
def split(data, *, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


alias("split", "SliceChannel")


@register("tile")
def tile(data, *, reps):
    return jnp.tile(data, tuple(reps))


@register("repeat")
def repeat(data, *, repeats, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad")
def pad(data, *, mode="constant", pad_width=None, constant_value=0.0):
    # MXNet pad_width is flat (before,after) per axis
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(data.ndim)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    return jnp.pad(data, pw, mode={"edge": "edge", "reflect": "reflect"}[mode])


alias("pad", "Pad")


@register("flip")
def flip(data, *, axis):
    return jnp.flip(data, axis=axis)


alias("flip", "reverse")


@register("swapaxes")
def swapaxes(data, *, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


alias("swapaxes", "SwapAxis")


@register("depth_to_space")
def depth_to_space(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def space_to_depth(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


# ---------------------------------------------------------------------------
# dot / linalg
# ---------------------------------------------------------------------------

@register("dot")
def dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: reduce over last axis of a and first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)




@register("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

@register("take")
def take(a, indices, *, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode != "wrap" else "wrap")


@register("pick")
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, *, shape):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register("one_hot")
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import normalize_dtype
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    out = oh * on_value + (1.0 - oh) * off_value
    return out.astype(normalize_dtype(dtype))


@register("boolean_mask_dense")
def boolean_mask_dense(data, mask):
    # dynamic-shape op: not traceable; eager-only fallback
    import numpy as np
    return jnp.asarray(np.asarray(data)[np.asarray(mask).astype(bool)])


# ---------------------------------------------------------------------------
# ordering
# ---------------------------------------------------------------------------

@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort")
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import normalize_dtype
    out = jnp.argsort(data if is_ascend else -data, axis=axis)
    return out.astype(normalize_dtype(dtype))


@register("topk", num_outputs=lambda p: 2 if p.get("ret_typ") == "both" else 1)
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import normalize_dtype
    d = jnp.moveaxis(data, axis, -1)
    vals, raw_idx = jax.lax.top_k(-d if is_ascend else d, k)
    if is_ascend:
        vals = -vals
    if ret_typ == "mask":
        # 1 at every top-k position, 0 elsewhere, in the DATA's layout
        # (reference ordering_op ReturnType::kReturnMask); built from the
        # raw integer indices before any float cast
        onehot = jax.nn.one_hot(raw_idx, d.shape[-1], dtype=data.dtype)
        return jnp.moveaxis(onehot.sum(axis=-2), -1, axis)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(raw_idx, -1, axis).astype(normalize_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    return idx


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

@register("diag")
def diag(data, *, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("shape_array")
def shape_array(data):
    return jnp.asarray(data.shape, dtype=_index_dtype())


@register("size_array")
def size_array(data):
    return jnp.asarray([data.size], dtype=_index_dtype())


@register("histogram", num_outputs=2)
def histogram(data, *, bin_cnt=10, range=None):
    lo, hi = range if range is not None else (float(data.min()), float(data.max()))
    counts, edges = jnp.histogram(data, bins=bin_cnt, range=(lo, hi))
    return counts.astype(_index_dtype()), edges.astype(data.dtype)


@register("ravel_multi_index")
def ravel_multi_index(data, *, shape):
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), dtype=data.dtype)
    return jnp.sum(data * strides[:, None], axis=0)


@register("unravel_index")
def unravel_index(data, *, shape):
    idx = data.astype(_index_dtype())
    out = []
    for s in reversed(shape):
        out.append(idx % s)
        idx = idx // s
    return jnp.stack(list(reversed(out)), axis=0).astype(data.dtype)


@register("sequence_mask")
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data * 1.0
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :].astype(steps.dtype)  # (T, B)
    shape = [1] * data.ndim
    shape[axis] = maxlen
    batch_axis = 1 if axis == 0 else 0
    shape[batch_axis] = data.shape[batch_axis]
    mask = jnp.reshape(mask if axis == 0 else mask.T, shape)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


alias("sequence_mask", "SequenceMask")


@register("sequence_last")
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length - 1).astype(jnp.int32)
    d = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jax.vmap(lambda t, i: t[i], in_axes=(1, 0))(d, idx)


alias("sequence_last", "SequenceLast")


@register("sequence_reverse")
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    d = jnp.moveaxis(data, axis, 0)
    T = d.shape[0]
    steps = jnp.arange(T)

    def rev_one(col, L):
        idx = jnp.where(steps < L, L - 1 - steps, steps)
        return col[idx]

    out = jax.vmap(rev_one, in_axes=(1, 0), out_axes=1)(d, sequence_length.astype(jnp.int32))
    return jnp.moveaxis(out, 0, axis)


alias("sequence_reverse", "SequenceReverse")


def _param_dtype_out(in_dtypes, params):
    """argsort/topk indices take the `dtype` param (default f32), not the
    input dtype; topk ret_typ=value/both lead with the input dtype."""
    import numpy as _np2
    from ..base import normalize_dtype
    idx_dt = _np2.dtype(normalize_dtype(params.get("dtype", "float32")))
    d = in_dtypes[0] if in_dtypes and in_dtypes[0] is not None \
        else _np2.dtype("float32")
    ret = params.get("ret_typ", "indices")
    if ret == "value":
        return list(in_dtypes), [d]
    if ret == "both":
        return list(in_dtypes), [d, idx_dt]
    return list(in_dtypes), [idx_dt]


from .registry import set_op_meta as _set_op_meta  # noqa: E402
_set_op_meta("argsort", dtype_hook=_param_dtype_out)
_set_op_meta("topk", dtype_hook=_param_dtype_out)


@register("reshape_like")
def reshape_like(lhs, rhs, *, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to the shape of rhs (parity:
    src/operator/tensor/elemwise_unary_op_basic.cc:429 — gradient flows to
    lhs only; rhs contributes shape, not values). The begin/end ranges
    replace ONLY lhs dims [lhs_begin, lhs_end) with rhs dims
    [rhs_begin, rhs_end), keeping the rest of lhs's shape (reference
    ReshapeLikeParam)."""

    def _rng(b, e, ndim, what):
        b = 0 if b is None else (b + ndim if b < 0 else b)
        e = ndim if e is None else (e + ndim if e < 0 else e)
        if not (0 <= b <= e <= ndim):   # reference GetReshapeLikeParams
            raise ValueError(
                "reshape_like: invalid %s range [%s, %s) for %d dims"
                % (what, b, e, ndim))
        return b, e

    lb, le = _rng(lhs_begin, lhs_end, lhs.ndim, "lhs")
    rb, re = _rng(rhs_begin, rhs_end, rhs.ndim, "rhs")
    shape = lhs.shape[:lb] + rhs.shape[rb:re] + lhs.shape[le:]
    return jnp.reshape(lhs, shape)


@register("batch_take")
def batch_take(a, indices):
    """out[i] = a[i, indices[i]] (parity:
    src/operator/tensor/indexing_op.cc:730 — deprecated alias of pick
    along axis 1)."""
    idx = indices.astype(_index_dtype()).reshape((-1,))
    return jnp.take_along_axis(
        a, idx[:, None], axis=1).reshape(idx.shape)


def _slice_tuple(shape, begin, end, step=None):
    """MXNet SliceParam begin/end/step (entries may be None) -> python
    slice tuple over leading len(begin) axes."""
    step = step if step is not None and len(step) else (None,) * len(begin)
    out = []
    for b, e, s in zip(begin, end, step):
        out.append(slice(b, e, s))
    return tuple(out)


@register("_slice_assign")
def slice_assign(lhs, rhs, *, begin, end, step=None):
    """Write rhs into lhs[begin:end:step] (parity:
    src/operator/tensor/matrix_op.cc:434 _slice_assign/_crop_assign).
    XLA scatters in place when the buffer is donated; under jit the
    functional update fuses."""
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(rhs)


@register("_slice_assign_scalar")
def slice_assign_scalar(data, *, scalar=0.0, begin=(), end=(), step=None):
    """Fill data[begin:end:step] with a scalar (parity:
    src/operator/tensor/matrix_op.cc:459)."""
    return data.at[_slice_tuple(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, data.dtype))


alias("_slice_assign", "_crop_assign")
alias("_slice_assign_scalar", "_crop_assign_scalar")
