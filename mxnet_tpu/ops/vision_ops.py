"""Vision operators the reference test suite exercises: Correlation, Crop
(v1), DeformableConvolution, Proposal, SyncBatchNorm.

Reference kernels: src/operator/correlation.cc, src/operator/crop.cc,
src/operator/contrib/deformable_convolution.cc (+ deformable_im2col),
src/operator/contrib/proposal.cc, src/operator/contrib/sync_batch_norm.cc.

TPU-native notes: everything is static-shaped, vectorized jnp (gradients
via jax autodiff — no hand-written backward kernels); Proposal emits a
fixed rpn_post_nms_top_n rows with -1 padding (the reference pads by
repeating; -1 rows match our box_nms convention); SyncBatchNorm is
BatchNorm — under SPMD with the batch axis sharded, XLA computes the
cross-replica statistics automatically, which IS the sync the reference
implements by hand with AllReduce (sync_batch_norm.cc).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias, get as _get_op, set_op_meta


# ---------------------------------------------------------------------------
# Correlation (FlowNet; reference src/operator/correlation.cc:33-82)
# ---------------------------------------------------------------------------

@register("Correlation")
def correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    k = int(kernel_size)
    md, s1, s2, p = int(max_displacement), int(stride1), int(stride2), \
        int(pad_size)
    n, c, h, w = data1.shape
    t1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    t2 = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    hp, wp = h + 2 * p, w + 2 * p
    kr = (k - 1) // 2
    border = md + kr
    top_h = -(-(hp - 2 * border) // s1)   # ceil
    top_w = -(-(wp - 2 * border) // s1)
    ngr = md // s2
    ngw = 2 * ngr + 1
    sumelems = k * k * c
    ones = jnp.ones((1, 1, k, k), t1.dtype)

    def boxsum(x):  # (n, hp', wp') -> valid kxk window sums
        return lax.conv_general_dilated(
            x[:, None], ones, (1, 1), "VALID")[:, 0]

    outs = []
    for ti in range(ngw * ngw):
        s2o = (ti % ngw - ngr) * s2
        s2p = (ti // ngw - ngr) * s2
        shifted = jnp.roll(t2, shift=(-s2p, -s2o), axis=(2, 3))
        prod = (t1 * shifted) if is_multiply else jnp.abs(t1 - shifted)
        summed = boxsum(prod.sum(axis=1))  # (n, hp-k+1, wp-k+1)
        # out[i,j] = window starting at (i*s1+md - kr + kr, ...) ==
        # boxsum index y1 = i*s1 + md - ... window top-left = y1 (x1)
        # where y1 = i*s1 + md maps into boxsum at y1 - 0 since boxsum
        # index is the window's top-left in the padded map
        sl = summed[:, md:md + top_h * s1:s1, md:md + top_w * s1:s1]
        outs.append(sl / sumelems)
    return jnp.stack(outs, axis=1)


# ---------------------------------------------------------------------------
# Crop v1 (reference src/operator/crop.cc — center/offset crop to h_w or to
# a reference symbol's spatial size)
# ---------------------------------------------------------------------------

@register("Crop")
def crop_v1(data, crop_like=None, *, offset=(0, 0), h_w=(0, 0),
            center_crop=False, num_args=1):
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
        if th <= 0 or tw <= 0:
            raise ValueError("Crop without crop_like needs h_w")
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (h - th) // 2, (w - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    return data[:, :, y0:y0 + th, x0:x0 + tw]


set_op_meta("Crop", num_visible_outputs=1)


# ---------------------------------------------------------------------------
# DeformableConvolution (reference contrib/deformable_convolution.cc via
# deformable_im2col: bilinear sampling at offset kernel taps, then GEMM)
# ---------------------------------------------------------------------------

def _bilinear_gather(img, ys, xs):
    """img (C,H,W); ys/xs (...,): bilinear sample, zero outside."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    vals = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            ok = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            v = img[:, yc, xc]  # (C, ...)
            vals = vals + v * (wy * wx * ok)[None]
    return vals


@register("_contrib_DeformableConvolution")
def deformable_convolution(data, offset, weight, bias=None, *, kernel,
                           num_filter, stride=(1, 1), pad=(0, 0),
                           dilate=(1, 1), num_deformable_group=1,
                           num_group=1, no_bias=False, workspace=1024,
                           layout="NCHW"):
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    dg = int(num_deformable_group)
    n, c, h, w = data.shape
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    # offsets: (N, 2*dg*kh*kw, oh, ow), channel ((g*kh+a)*kw+b)*2 + {y,x}
    off = offset.reshape(n, dg, kh * kw, 2, oh, ow)

    def sample_one(img, off_b):
        # img (C,H,W), off_b (dg, kh*kw, 2, oh, ow)
        cols = []
        cpg = c // dg  # channels per deformable group
        for g in range(dg):
            taps = []
            for a in range(kh):
                for b_ in range(kw):
                    t = a * kw + b_
                    ys = (jnp.arange(oh) * sh - ph + a * dh)[:, None] \
                        + off_b[g, t, 0]
                    xs = (jnp.arange(ow) * sw - pw + b_ * dw)[None, :] \
                        + off_b[g, t, 1]
                    taps.append(_bilinear_gather(
                        img[g * cpg:(g + 1) * cpg], ys, xs))
            cols.append(jnp.stack(taps, axis=1))  # (cpg, kh*kw, oh, ow)
        return jnp.concatenate(cols, axis=0)  # (C, kh*kw, oh, ow)

    sampled = jax.vmap(sample_one)(data, off)  # (N, C, kh*kw, oh, ow)
    wmat = weight.reshape(num_filter, -1)  # (F, C/ng * kh*kw)
    ng = int(num_group)
    cg = c // ng
    fg = num_filter // ng
    outs = []
    for g in range(ng):
        sg = sampled[:, g * cg:(g + 1) * cg].reshape(n, cg * kh * kw, oh, ow)
        wg = wmat[g * fg:(g + 1) * fg]
        outs.append(jnp.einsum("fk,nkhw->nfhw", wg, sg))
    out = jnp.concatenate(outs, axis=1)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _deform_conv_shapes(in_shapes, params):
    dshape = in_shapes[0]
    if dshape is None:
        return in_shapes
    kh, kw = (int(x) for x in params["kernel"])
    nf = int(params["num_filter"])
    ng = int(params.get("num_group", 1))
    dg = int(params.get("num_deformable_group", 1))
    stride = params.get("stride", (1, 1))
    pad = params.get("pad", (0, 0))
    dilate = params.get("dilate", (1, 1))
    n, c, h, w = dshape
    oh = (h + 2 * int(pad[0]) - (int(dilate[0]) * (kh - 1) + 1)) \
        // int(stride[0]) + 1
    ow = (w + 2 * int(pad[1]) - (int(dilate[1]) * (kw - 1) + 1)) \
        // int(stride[1]) + 1
    completed = list(in_shapes)
    completed[1] = (n, 2 * dg * kh * kw, oh, ow)
    completed[2] = (nf, c // ng, kh, kw)
    if len(completed) > 3 and completed[3] is None and \
            not params.get("no_bias", False):
        completed[3] = (nf,)
    return completed


set_op_meta("_contrib_DeformableConvolution", shape_hook=_deform_conv_shapes)


# ---------------------------------------------------------------------------
# Proposal (RPN; reference src/operator/contrib/proposal.cc)
# ---------------------------------------------------------------------------

def _make_anchors(base_size, scales, ratios):
    """Reference GenerateAnchors (proposal.cc): base box (0,0,bs-1,bs-1),
    ratio enum then scale enum."""
    bs = float(base_size)
    px, py = (bs - 1) * 0.5, (bs - 1) * 0.5
    size = bs * bs
    anchors = []
    for r in ratios:
        size_ratio = size / r
        ws = round(_np.sqrt(size_ratio))
        hs = round(ws * r)
        for s in scales:
            w2, h2 = ws * s, hs * s
            anchors.append([px - (w2 - 1) * 0.5, py - (h2 - 1) * 0.5,
                            px + (w2 - 1) * 0.5, py + (h2 - 1) * 0.5])
    return _np.asarray(anchors, _np.float32)


@register("_contrib_Proposal",
          num_outputs=lambda p: 2 if p.get("output_score") else 1)
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    n, _, fh, fw = cls_prob.shape
    A = len(scales) * len(ratios)
    base = _make_anchors(feature_stride, scales, ratios)  # (A, 4)
    shift_x = jnp.arange(fw) * feature_stride
    shift_y = jnp.arange(fh) * feature_stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)  # (fh, fw)
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 4)
    anchors = (base[None] + shifts[:, None]).reshape(-1, 4)  # (fh*fw*A, 4)

    def one(scores_map, deltas_map, info):
        # scores: fg channels (A..2A); layout (A, fh, fw) -> (fh*fw*A,)
        scores = scores_map[A:].transpose(1, 2, 0).reshape(-1)
        d = deltas_map.reshape(A, 4, fh, fw).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        if iou_loss:
            # IoU-loss decode: deltas are direct corner offsets
            # (proposal.cc IoUTransformInv)
            x1 = anchors[:, 0] + d[:, 0]
            y1 = anchors[:, 1] + d[:, 1]
            x2 = anchors[:, 2] + d[:, 2]
            y2 = anchors[:, 3] + d[:, 3]
        else:
            widths = anchors[:, 2] - anchors[:, 0] + 1.0
            heights = anchors[:, 3] - anchors[:, 1] + 1.0
            ctr_x = anchors[:, 0] + 0.5 * (widths - 1)
            ctr_y = anchors[:, 1] + 0.5 * (heights - 1)
            pred_x = d[:, 0] * widths + ctr_x
            pred_y = d[:, 1] * heights + ctr_y
            pred_w = jnp.exp(d[:, 2]) * widths
            pred_h = jnp.exp(d[:, 3]) * heights
            x1 = pred_x - 0.5 * (pred_w - 1)
            y1 = pred_y - 0.5 * (pred_h - 1)
            x2 = pred_x + 0.5 * (pred_w - 1)
            y2 = pred_y + 0.5 * (pred_h - 1)
        # clip to image
        imh, imw = info[0], info[1]
        x1 = jnp.clip(x1, 0, imw - 1.0)
        y1 = jnp.clip(y1, 0, imh - 1.0)
        x2 = jnp.clip(x2, 0, imw - 1.0)
        y2 = jnp.clip(y2, 0, imh - 1.0)
        # min-size filter (scaled by im_info[2])
        min_sz = rpn_min_size * info[2]
        keep = ((x2 - x1 + 1) >= min_sz) & ((y2 - y1 + 1) >= min_sz)
        scores = jnp.where(keep, scores, -1.0)
        pre_n = min(rpn_pre_nms_top_n, scores.shape[0]) \
            if rpn_pre_nms_top_n > 0 else scores.shape[0]
        top_scores, order = lax.top_k(scores, pre_n)
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)[order]
        # greedy NMS over the pre-nms set
        packed = jnp.concatenate(
            [jnp.zeros((pre_n, 1)), top_scores[:, None], boxes], axis=1)
        nms = _get_op("_contrib_box_nms").fn(
            packed, overlap_thresh=threshold, valid_thresh=0.0,
            topk=rpn_post_nms_top_n, coord_start=2, score_index=1,
            id_index=-1, force_suppress=True)
        kept = nms[:, 1] >= 0
        # compact the survivors to the front, pad with -1 rows
        idx = jnp.argsort(~kept, stable=True)[:rpn_post_nms_top_n]
        rows = nms[idx]
        valid = kept[idx]
        rois = jnp.where(valid[:, None], rows[:, 2:6],
                         -jnp.ones_like(rows[:, 2:6]))
        rscores = jnp.where(valid, rows[:, 1], -jnp.ones_like(rows[:, 1]))
        # fewer anchors than rpn_post_nms_top_n: pad to the fixed output
        # contract (reference always emits rpn_post_nms_top_n rows)
        short = rpn_post_nms_top_n - rois.shape[0]
        if short > 0:
            rois = jnp.concatenate(
                [rois, -jnp.ones((short, 4), rois.dtype)], axis=0)
            rscores = jnp.concatenate(
                [rscores, -jnp.ones((short,), rscores.dtype)], axis=0)
        return rois, rscores

    rois, rscores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.broadcast_to(
        jnp.arange(n, dtype=rois.dtype)[:, None, None],
        (n, rois.shape[1], 1))
    out = jnp.concatenate([batch_idx, rois], axis=2) \
        .reshape(-1, 5)
    if output_score:
        return out, rscores.reshape(-1, 1)
    return out


alias("_contrib_Proposal", "Proposal")


# ---------------------------------------------------------------------------
# SyncBatchNorm: on TPU this IS BatchNorm — with the batch axis sharded
# over the mesh, XLA's sharding propagation makes jnp.mean/var over the
# batch a cross-replica reduction, which is exactly the AllReduce the
# reference hand-writes in src/operator/contrib/sync_batch_norm.cc. The
# `key`/`ndev` bookkeeping of the reference's host barrier is unnecessary.
# ---------------------------------------------------------------------------

@register("_contrib_SyncBatchNorm", num_outputs=5)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key=None,
                    _training=True):
    from .nn import batch_norm
    return batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                      momentum=momentum, fix_gamma=fix_gamma,
                      use_global_stats=use_global_stats,
                      output_mean_var=output_mean_var, _training=_training)


from .nn import _bn_shapes as _nn_bn_shapes  # noqa: E402
from .nn import _bn_dtypes as _nn_bn_dtypes  # noqa: E402
set_op_meta("_contrib_SyncBatchNorm", shape_hook=_nn_bn_shapes,
            dtype_hook=_nn_bn_dtypes, aux_inputs=(3, 4), aux_outputs=(3, 4),
            num_visible_outputs=lambda p: 3 if p.get("output_mean_var")
            else 1)
alias("_contrib_SyncBatchNorm", "SyncBatchNorm")
alias("Correlation", "_contrib_Correlation")


# ---------------------------------------------------------------------------
# Position-sensitive ROI pooling (reference src/operator/contrib/
# psroi_pooling.cc:43-112 loop nest): each output cell (ctop, ph, pw)
# averages ONE position-specific channel c = (ctop*G + gh)*G + gw over its
# bin. XLA-friendly form: static-shape bin masks over the full H x W
# contracted against the gathered channel map — no dynamic slices.
# ---------------------------------------------------------------------------

@register("_contrib_PSROIPooling")
def psroi_pooling(data, rois, *, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    group_size = group_size or pooled_size
    n, channels, height, width = data.shape
    ph = pw = pooled_size
    g = group_size

    hh = jnp.arange(height, dtype=jnp.float32)
    ww = jnp.arange(width, dtype=jnp.float32)
    p_idx = jnp.arange(ph, dtype=jnp.float32)

    # channel index per (ctop, ph, pw)
    gh = jnp.clip((jnp.arange(ph) * g) // ph, 0, g - 1)
    gw = jnp.clip((jnp.arange(pw) * g) // pw, 0, g - 1)
    ctop = jnp.arange(output_dim)
    c_idx = (ctop[:, None, None] * g + gh[None, :, None]) * g \
        + gw[None, None, :]                                     # (D,ph,pw)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        start_w = jnp.round(roi[1]) * spatial_scale
        start_h = jnp.round(roi[2]) * spatial_scale
        end_w = (jnp.round(roi[3]) + 1.0) * spatial_scale
        end_h = (jnp.round(roi[4]) + 1.0) * spatial_scale
        roi_w = jnp.maximum(end_w - start_w, 0.1)
        roi_h = jnp.maximum(end_h - start_h, 0.1)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        hstart = jnp.clip(jnp.floor(p_idx * bin_h + start_h), 0, height)
        hend = jnp.clip(jnp.ceil((p_idx + 1) * bin_h + start_h), 0, height)
        wstart = jnp.clip(jnp.floor(p_idx * bin_w + start_w), 0, width)
        wend = jnp.clip(jnp.ceil((p_idx + 1) * bin_w + start_w), 0, width)
        mh = ((hh[None, :] >= hstart[:, None])
              & (hh[None, :] < hend[:, None])).astype(jnp.float32)  # (ph,H)
        mw = ((ww[None, :] >= wstart[:, None])
              & (ww[None, :] < wend[:, None])).astype(jnp.float32)  # (pw,W)
        img = jnp.take(data, b, axis=0)            # (C,H,W)
        # contract bins on the raw image FIRST (C,p,p intermediate), then
        # pick position-sensitive channels — gathering to (D,p,p,H,W)
        # before the contraction would inflate peak memory by p^2
        s_all = jnp.einsum("chw,ph,qw->cpq", img, mh, mw)
        s = s_all[c_idx,
                  jnp.arange(ph)[None, :, None],
                  jnp.arange(pw)[None, None, :]]   # (D,ph,pw)
        area = (hend - hstart)[:, None] * (wend - wstart)[None, :]
        return jnp.where(area > 0, s / jnp.maximum(area, 1.0), 0.0)

    return jax.vmap(one)(rois)                     # (R, D, ph, pw)


# ---------------------------------------------------------------------------
# Deformable PS-ROI pooling (reference _contrib_DeformablePSROIPooling,
# deformable_psroi_pooling.cu kernel semantics / arXiv:1703.06211): bins
# shift by learned normalized offsets `trans` and sample
# sample_per_part^2 points bilinearly; out-of-image samples are dropped
# from the average. Gradients (incl. through trans) come from autodiff.
# ---------------------------------------------------------------------------

@register("_contrib_DeformablePSROIPooling")
def deformable_psroi_pooling(data, rois, trans=None, *, spatial_scale,
                             output_dim, group_size, pooled_size,
                             part_size=0, sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    n, channels, height, width = data.shape
    p = pooled_size
    g = group_size
    part = part_size or p
    sp = sample_per_part

    gh = jnp.clip((jnp.arange(p) * g) // p, 0, g - 1)
    gw = jnp.clip((jnp.arange(p) * g) // p, 0, g - 1)
    ctop = jnp.arange(output_dim)
    c_idx = (ctop[:, None, None] * g + gh[None, :, None]) * g \
        + gw[None, None, :]                                    # (D,p,p)
    part_h = jnp.clip((jnp.arange(p) * part) // p, 0, part - 1)
    part_w = part_h

    if not no_trans and trans is not None:
        num_classes = trans.shape[1] // 2
        cls_of_ctop = (ctop * num_classes) // output_dim       # (D,)

    def one(roi, tr):
        b = roi[0].astype(jnp.int32)
        start_w = jnp.round(roi[1]) * spatial_scale - 0.5
        start_h = jnp.round(roi[2]) * spatial_scale - 0.5
        end_w = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        end_h = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        roi_w = jnp.maximum(end_w - start_w, 0.1)
        roi_h = jnp.maximum(end_h - start_h, 0.1)
        bin_h = roi_h / p
        bin_w = roi_w / p
        sub_h = bin_h / sp
        sub_w = bin_w / sp

        if no_trans or tr is None:
            tx = jnp.zeros((output_dim, p, p))
            ty = jnp.zeros((output_dim, p, p))
        else:
            # trans: (2*num_classes, part, part); offsets per class & part
            tx_all = tr[cls_of_ctop * 2][:, part_h][:, :, part_w]
            ty_all = tr[cls_of_ctop * 2 + 1][:, part_h][:, :, part_w]
            tx = tx_all * trans_std
            ty = ty_all * trans_std

        # sample grid: (D, p, p, sp, sp)
        ph_idx = jnp.arange(p, dtype=jnp.float32)
        base_h = ph_idx[:, None] * bin_h + start_h              # (p,1)
        base_w = ph_idx[None, :] * bin_w + start_w              # (1,p)
        ih = jnp.arange(sp, dtype=jnp.float32)
        hh = (base_h[None, :, :, None, None] + ty[..., None, None] * roi_h
              + ih[None, None, None, :, None] * sub_h)
        wwv = (base_w[None, :, :, None, None] + tx[..., None, None] * roi_w
               + ih[None, None, None, None, :] * sub_w)
        # boundary-equal samples stay valid (reference kernel drops only
        # w < -0.5 || w > width-0.5): ROIs touching the image edge land
        # exactly on -0.5 and must count in the average
        valid = ((hh >= -0.5) & (hh <= height - 0.5)
                 & (wwv >= -0.5) & (wwv <= width - 0.5))
        hc = jnp.clip(hh, 0.0, height - 1.0)
        wc = jnp.clip(wwv, 0.0, width - 1.0)
        h0 = jnp.floor(hc).astype(jnp.int32)
        w0 = jnp.floor(wc).astype(jnp.int32)
        h1 = jnp.minimum(h0 + 1, height - 1)
        w1 = jnp.minimum(w0 + 1, width - 1)
        ah = hc - h0
        aw = wc - w0

        # bilinear gather straight from the flat (C*H*W) image: combined
        # channel+spatial flat indices per sample point — never the
        # (D,p,p,H,W) gathered intermediate (p^2 memory inflation, same
        # reasoning as psroi_pooling above)
        imgf = jnp.take(data, b, axis=0).reshape(-1)           # (C*H*W,)

        def take(hi, wi):
            idx = (c_idx[..., None, None] * (height * width)
                   + hi * width + wi)                          # (D,p,p,sp,sp)
            return imgf[idx]

        v00 = take(h0, w0)
        v01 = take(h0, w1)
        v10 = take(h1, w0)
        v11 = take(h1, w1)
        sample = ((1 - ah) * (1 - aw) * v00 + (1 - ah) * aw * v01
                  + ah * (1 - aw) * v10 + ah * aw * v11)
        sample = jnp.where(valid, sample, 0.0)
        cnt = jnp.sum(valid, axis=(-2, -1))
        s = jnp.sum(sample, axis=(-2, -1))
        return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), 0.0)

    if trans is None or no_trans:
        return jax.vmap(lambda r: one(r, None))(rois)
    return jax.vmap(one)(rois, trans if trans.shape[0] == rois.shape[0]
                         else jnp.broadcast_to(
                             trans, (rois.shape[0],) + trans.shape[1:]))


@register("_contrib_quadratic")
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c (reference contrib/quadratic_op.cc:31 — the
    "tutorial op"; kept for script parity)."""
    return a * data * data + b * data + c


@register("_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    """data / sqrt(last_dim) (reference contrib/transformer.cc:33 — the
    attention-score scaling helper)."""
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


# MultiProposal IS the batched Proposal here: proposal() already vmaps
# over the batch (reference multi_proposal.cc duplicates proposal.cc for
# batch>1)
alias("_contrib_Proposal", "_contrib_MultiProposal", "MultiProposal")
