"""Central operator registry.

The reference keeps a single NNVM registry consumed by both the imperative
runtime and the symbolic executor (SURVEY.md §1; reference:
include/mxnet/op_attr_types.h, src/operator/nn/fully_connected.cc:239-326 for
the registration pattern). We keep that key design point — one registry, two
front-ends — but each op is a **pure JAX function**:

* gradients come from ``jax.vjp`` (no hand-written FGradient),
* shape/type inference comes from ``jax.eval_shape`` (no FInferShape),
* CPU/TPU portability comes from XLA (no per-device kernels),
* fusion/memory planning come from ``jax.jit`` (no PlanMemory pass).

Op functions take positional array arguments followed by keyword hyper
parameters and return one array or a tuple of arrays. Ops that need
randomness draw keys via :mod:`mxnet_tpu.random` (stateful facade; traced
graphs thread an explicit key input).
"""
from __future__ import annotations

import functools
import inspect

__all__ = ["Operator", "register", "get", "list_ops", "alias"]

_REGISTRY: dict[str, "Operator"] = {}


class Operator:
    """A registered op: a pure jax fn + metadata for the two front-ends."""

    __slots__ = ("name", "fn", "num_outputs", "param_names", "is_random",
                 "doc", "shape_hook", "dtype_hook", "aux_inputs",
                 "aux_outputs", "num_visible_outputs", "input_names",
                 "input_optional", "has_var_inputs")

    def __init__(self, name, fn, num_outputs=1, is_random=False):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs  # int, or callable(params)->int
        self.is_random = is_random
        self.doc = fn.__doc__ or ""
        # symbolic-layer metadata (set via set_op_meta):
        self.shape_hook = None        # fn(in_shapes, params) -> completed in_shapes
        self.dtype_hook = None        # fn(in_dtypes, params) -> (in_dtypes, out_dtypes)
        self.aux_inputs = ()          # input slots that are auxiliary states
        self.aux_outputs = ()         # output slots holding updated aux values
        self.num_visible_outputs = None  # outputs exposed to the graph (prefix)
        sig = inspect.signature(fn)
        self.param_names = [
            p.name for p in sig.parameters.values()
            if p.kind == inspect.Parameter.KEYWORD_ONLY
        ]
        # positional (array) inputs: name -> has_default
        self.input_names = []
        self.input_optional = []
        self.has_var_inputs = False
        for p in sig.parameters.values():
            if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                          inspect.Parameter.POSITIONAL_ONLY):
                self.input_names.append(p.name)
                self.input_optional.append(p.default is not inspect.Parameter.empty)
            elif p.kind == inspect.Parameter.VAR_POSITIONAL:
                self.has_var_inputs = True

    def bind_positional(self, args, kwargs):
        """Split positional call args into (input_args, kwargs): anything
        past the declared tensor-input slots binds to param_names in
        declaration order — the reference's generated-signature contract
        (mx.nd.reshape(x, (3, 2)), mx.nd.sum(x, 1)). Variadic-input ops
        treat every positional as an input."""
        if self.has_var_inputs or len(args) <= len(self.input_names):
            return args, kwargs
        extra = args[len(self.input_names):]
        if len(extra) > len(self.param_names):
            raise TypeError("%s: too many positional arguments" % self.name)
        for pname, val in zip(self.param_names, extra):
            if pname in kwargs:
                raise TypeError("%s: parameter %r given positionally and "
                                "by keyword" % (self.name, pname))
            kwargs[pname] = val
        return args[:len(self.input_names)], kwargs

    def resolve_num_outputs(self, params):
        if callable(self.num_outputs):
            return self.num_outputs(params)
        return self.num_outputs

    def resolve_num_visible_outputs(self, params):
        """Outputs exposed to the graph (reference FNumVisibleOutputs);
        the hidden suffix carries updated aux state."""
        if self.num_visible_outputs is None:
            return self.resolve_num_outputs(params)
        if callable(self.num_visible_outputs):
            return self.num_visible_outputs(params)
        return self.num_visible_outputs

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return "Operator(%s)" % self.name


def register(name=None, num_outputs=1, is_random=False):
    """Decorator: register a pure jax function as an operator."""
    def deco(fn):
        opname = name or fn.__name__
        op = Operator(opname, fn, num_outputs=num_outputs, is_random=is_random)
        if opname in _REGISTRY:
            raise ValueError("duplicate op registration: %s" % opname)
        _REGISTRY[opname] = op
        return fn
    return deco


def set_op_meta(name, shape_hook=None, dtype_hook=None, aux_inputs=None,
                aux_outputs=None, num_visible_outputs=None):
    """Attach symbolic-layer metadata (parameter-shape/dtype inference
    hooks and auxiliary-state slots — the reference's FInferShape /
    FInferType / aux_states)."""
    op = _REGISTRY[name]
    if shape_hook is not None:
        op.shape_hook = shape_hook
    if dtype_hook is not None:
        op.dtype_hook = dtype_hook
    if aux_inputs is not None:
        op.aux_inputs = tuple(aux_inputs)
    if aux_outputs is not None:
        op.aux_outputs = tuple(aux_outputs)
    if num_visible_outputs is not None:
        op.num_visible_outputs = num_visible_outputs
    return op


def alias(existing, *names):
    op = _REGISTRY[existing]
    for n in names:
        _REGISTRY[n] = op
    return op


def get(name) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError("operator %r is not registered (have %d ops)"
                       % (name, len(_REGISTRY)))


def get_or_none(name):
    return _REGISTRY.get(name)


def list_ops():
    return sorted(_REGISTRY.keys())


def namespaced_surface(module_globals, make_fn, resolve, listing=None):
    """Generic generated-namespace machinery (mx.nd.op / mx.nd.image /
    mx.sym.random ... — reference code-generated namespace modules):
    returns (__getattr__, __dir__) where ``resolve(attr)`` maps the
    attribute to a registry op name (or None -> AttributeError) and
    ``listing()`` yields the dir() names."""
    def __getattr__(name):
        opname = resolve(name)
        op = get_or_none(opname) if opname else None
        if op is None:
            raise AttributeError(
                "%s has no attribute %r" % (module_globals.get(
                    "__name__", "<namespace>"), name))
        fn = make_fn(op)
        fn.__name__ = name
        module_globals[name] = fn   # cache for the next lookup
        return fn

    def __dir__():
        extra = list(listing()) if listing else []
        return sorted(set(list(module_globals) + extra))

    return __getattr__, __dir__


def contrib_surface(module_globals, make_fn):
    """mx.nd.contrib / mx.sym.contrib namespaces: ``name`` resolves to
    the registered ``_contrib_<name>`` operator."""
    return namespaced_surface(
        module_globals, make_fn,
        resolve=lambda n: "_contrib_" + n,
        listing=lambda: [n[len("_contrib_"):] for n in list_ops()
                         if n.startswith("_contrib_")])
