"""Creation ops (zeros/ones/full/arange/eye/linspace).

Parity: src/operator/tensor/init_op.cc. These take no array inputs.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, alias
from ..base import normalize_dtype


def _dt(dtype):
    return normalize_dtype(dtype or "float32")


@register("_zeros")
def zeros(*, shape=(), dtype="float32", ctx=None):
    return jnp.zeros(tuple(shape) if hasattr(shape, "__len__") else (shape,), _dt(dtype))


@register("_ones")
def ones(*, shape=(), dtype="float32", ctx=None):
    return jnp.ones(tuple(shape) if hasattr(shape, "__len__") else (shape,), _dt(dtype))


@register("_full")
def full(*, shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(tuple(shape) if hasattr(shape, "__len__") else (shape,),
                    value, _dt(dtype))


@register("_arange")
def arange(*, start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None,
           infer_range=False):
    out = jnp.arange(start, stop, step, _dt(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace")
def linspace(*, start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32", ctx=None):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=_dt(dtype))


@register("_eye")
def eye(*, N, M=0, k=0, dtype="float32", ctx=None):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=_dt(dtype))
