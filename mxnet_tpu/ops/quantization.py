"""Int8 quantization operators (parity: src/operator/quantization/ —
quantize/quantize_v2/dequantize/requantize + quantized_fully_connected /
quantized_conv; python surface python/mxnet/contrib/quantization.py).

TPU-native: int8 matmuls lower to lax.dot_general with an int32
accumulator, which XLA maps onto the MXU's integer path; the float32
scale/offset bookkeeping mirrors the reference's min/max-range calibration
scheme so calibrated models produce the same numerics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


def _range_of(out_type):
    if out_type == "uint8":
        return 0.0, 255.0
    if out_type == "int8":
        return -127.0, 127.0
    raise ValueError("unsupported quantized type %r" % out_type)


@register("_contrib_quantize", num_outputs=3)
def quantize(data, min_range, max_range, *, out_type="uint8"):
    """Quantize float data given calibration range (reference quantize op)."""
    lo = jnp.reshape(min_range, ())
    hi = jnp.reshape(max_range, ())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(hi - lo, 1e-8)
        q = jnp.clip(jnp.round((data - lo) * scale), 0, 255) \
            .astype(jnp.uint8)
    else:
        amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
        scale = 127.0 / jnp.maximum(amax, 1e-8)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
        lo, hi = -amax, amax
    return q, jnp.reshape(lo, (1,)), jnp.reshape(hi, (1,))


@register("_contrib_quantize_v2", num_outputs=3)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    if min_calib_range is None or max_calib_range is None:
        lo = jnp.min(data)
        hi = jnp.max(data)
    else:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    return quantize(data, lo, hi, out_type=out_type)


@register("_contrib_dequantize")
def dequantize(data, min_range, max_range, *, out_type="float32"):
    lo = jnp.reshape(min_range, ())
    hi = jnp.reshape(max_range, ())
    if data.dtype == jnp.uint8:
        scale = jnp.maximum(hi - lo, 1e-8) / 255.0
        return data.astype(jnp.float32) * scale + lo
    amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    if data.dtype == jnp.int32:  # accumulator from a quantized matmul/conv
        return data.astype(jnp.float32) * (amax / (2.0 ** 31 - 1))
    return data.astype(jnp.float32) * (amax / 127.0)


@register("_contrib_requantize", num_outputs=3)
def requantize(data, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None, out_type="int8"):
    """int32 accumulator -> int8 with a new calibrated range."""
    # float value represented by one int32 step
    in_scale = jnp.maximum(jnp.abs(jnp.reshape(min_range, ())),
                           jnp.abs(jnp.reshape(max_range, ()))) / \
        (2.0 ** 31 - 1)
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.asarray(min_calib_range, jnp.float32)
        hi = jnp.asarray(max_calib_range, jnp.float32)
    else:
        lo, hi = jnp.min(real), jnp.max(real)
    return quantize(real, lo, hi, out_type=out_type)


def _q_scale(lo, hi, dtype):
    if dtype == jnp.uint8:
        return 255.0 / jnp.maximum(hi - lo, 1e-8), lo
    amax = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    return 127.0 / jnp.maximum(amax, 1e-8), 0.0


@register("_contrib_quantized_fully_connected", num_outputs=3)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias, *,
                              num_hidden, no_bias=False, flatten=True):
    """int8 x int8 -> int32 FC (reference quantized_fully_connected)."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    acc = lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    d_lo, d_hi = jnp.reshape(min_data, ()), jnp.reshape(max_data, ())
    w_lo, w_hi = jnp.reshape(min_weight, ()), jnp.reshape(max_weight, ())
    d_scale, _ = _q_scale(d_lo, d_hi, data.dtype)
    w_scale, _ = _q_scale(w_lo, w_hi, weight.dtype)
    out_scale = 1.0 / (d_scale * w_scale)  # float value of one int32 step
    if not no_bias and bias is not None:
        b_lo, b_hi = jnp.reshape(min_bias, ()), jnp.reshape(max_bias, ())
        b_scale, _ = _q_scale(b_lo, b_hi, bias.dtype)
        b_int32 = jnp.round(bias.astype(jnp.float32) / b_scale
                            / out_scale).astype(jnp.int32)
        acc = acc + b_int32
    out_max = (2.0 ** 31 - 1) * out_scale
    return acc, jnp.reshape(-out_max, (1,)), jnp.reshape(out_max, (1,))


@register("_contrib_quantized_conv", num_outputs=3)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, *, kernel, num_filter,
                   stride=None, dilate=None, pad=None, num_group=1,
                   no_bias=False, layout=None):
    """int8 convolution with int32 accumulation (reference quantized_conv)."""
    n = len(kernel)
    stride = tuple(s if s else 1 for s in (stride or (1,) * n))
    dilate = tuple(d if d else 1 for d in (dilate or (1,) * n))
    padding = [(p, p) for p in (pad or (0,) * n)]
    fmt = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
           3: ("NCDHW", "OIDHW", "NCDHW")}[n]
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, fmt)
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=padding, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    d_lo, d_hi = jnp.reshape(min_data, ()), jnp.reshape(max_data, ())
    w_lo, w_hi = jnp.reshape(min_weight, ()), jnp.reshape(max_weight, ())
    d_scale, _ = _q_scale(d_lo, d_hi, data.dtype)
    w_scale, _ = _q_scale(w_lo, w_hi, weight.dtype)
    out_scale = 1.0 / (d_scale * w_scale)
    if not no_bias and bias is not None:
        b_lo, b_hi = jnp.reshape(min_bias, ()), jnp.reshape(max_bias, ())
        b_scale, _ = _q_scale(b_lo, b_hi, bias.dtype)
        b_int32 = jnp.round(bias.astype(jnp.float32) / b_scale
                            / out_scale).astype(jnp.int32)
        acc = acc + jnp.reshape(b_int32, (1, -1) + (1,) * n)
    out_max = (2.0 ** 31 - 1) * out_scale
    return acc, jnp.reshape(-out_max, (1,)), jnp.reshape(out_max, (1,))


@register("_contrib_quantized_flatten", num_outputs=3)
def quantized_flatten(data, min_range, max_range):
    return data.reshape(data.shape[0], -1), min_range, max_range


@register("_contrib_quantized_pooling", num_outputs=3)
def quantized_pooling(data, min_data, max_data, *, kernel=(), pool_type="max",
                      global_pool=False, stride=None, pad=None,
                      pooling_convention="valid", count_include_pad=True,
                      cudnn_off=False, p_value=2, layout=None):
    """Pooling over int8/uint8 feature maps (parity:
    src/operator/quantization/quantized_pooling.cc). Pooling is monotonic
    (max) or range-contained (avg), so min/max calibration ranges pass
    through unchanged; the arithmetic runs in int32 on the VPU and rounds
    back to the input dtype for avg."""
    from .nn import pooling as _pooling
    qdt = data.dtype
    out = _pooling(data.astype(jnp.float32), kernel=kernel,
                   pool_type=pool_type, global_pool=global_pool,
                   stride=stride, pad=pad,
                   pooling_convention=pooling_convention,
                   count_include_pad=count_include_pad)
    if pool_type == "max":
        out = out.astype(qdt)
    else:
        out = jnp.clip(jnp.round(out),
                       jnp.iinfo(qdt).min, jnp.iinfo(qdt).max).astype(qdt)
    return out, min_data, max_data


@register("_contrib_quantized_concat", num_outputs=3)
def quantized_concat(*args, dim=1, num_args=None):
    """Concat int8/uint8 inputs with differing calibration ranges (parity:
    src/operator/quantization/quantized_concat.cc): every input is
    rescaled into the widest [min, max] pair, and the output carries that
    union range. Inputs arrive as [d0..dn-1, min0, max0, min1, max1, ...]
    per the reference's input ordering (data first, then min/max pairs)."""
    n = num_args if num_args is not None else len(args) // 3
    data = args[:n]
    mins = [jnp.reshape(a, ()) for a in args[n::2]]
    maxs = [jnp.reshape(a, ()) for a in args[n + 1::2]]
    out_lo = mins[0]
    out_hi = maxs[0]
    for lo, hi in zip(mins[1:], maxs[1:]):
        out_lo = jnp.minimum(out_lo, lo)
        out_hi = jnp.maximum(out_hi, hi)
    # reference ConcatType: int8 if ANY input is int8, else uint8
    qdt = jnp.int8 if any(d.dtype == jnp.int8 for d in data) else jnp.uint8
    out_scale, out_zero = _q_scale(out_lo, out_hi, qdt)
    parts = []
    lo_q, hi_q = (0, 255) if qdt == jnp.uint8 else (-127, 127)
    for d, lo, hi in zip(data, mins, maxs):
        scale, zero = _q_scale(lo, hi, d.dtype)
        real = d.astype(jnp.float32) / scale + zero   # dequantize
        q = jnp.round((real - out_zero) * out_scale)  # requantize to union
        parts.append(jnp.clip(q, lo_q, hi_q).astype(qdt))
    return (jnp.concatenate(parts, axis=dim),
            jnp.reshape(out_lo, (1,)), jnp.reshape(out_hi, (1,)))


alias("_contrib_quantize", "quantize")
alias("_contrib_dequantize", "dequantize")
