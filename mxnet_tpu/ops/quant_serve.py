"""Serving-side int8 operators for post-training quantization
(mxnet_tpu/quant): the closed primitive set the rewrite pass lowers
eligible FullyConnected / Convolution sites onto.

Unlike the reference-parity ops in :mod:`ops/quantization` (runtime
min/max triples threaded through the graph), these bake the calibrated
activation scale as a STATIC hyperparameter and carry the per-output-
channel dequant scale / bias — with the inference BatchNorm affine and
any f32 bias already folded in — as small f32 parameter arrays. One op
per site:

    f32 data -> static-scale int8 quantize -> int8 x int8 dot/conv
    (int32 accumulate on the MXU) -> fused dequant epilogue
    ``act(acc * scale[oc] + bias[oc])`` -> f32

The epilogue dispatches through the PR-6 kernel tier
(``kernels/int8_dequant``, pure-JAX fallback). Inference only: no
custom_vjp, the quantized graph is never differentiated.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..kernels import tier as _tier
from .registry import register

__all__ = ["quantized_fc_int8", "quantized_conv_int8"]


def _quantize_static(data, act_scale):
    """f32 -> int8 with the calibrated per-tensor scale (symmetric)."""
    q = jnp.round(data.astype(jnp.float32) * jnp.float32(act_scale))
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def _epilogue(acc, scale, bias, channel_axis, act):
    """Fused dequant->affine->act over the int32 accumulator; kernel-tier
    dispatched with a pure-JAX fallback (models never see the difference
    except in speed)."""
    from ..kernels import int8_dequant as _k
    if channel_axis == 1 and acc.ndim == 4:
        N, C, H, W = acc.shape
        acc2 = acc.reshape(N * C, H * W)
        sc = jnp.tile(scale.astype(jnp.float32), N)[:, None]
        sh = jnp.tile(bias.astype(jnp.float32), N)[:, None]
        per_row = True
    else:
        acc2 = acc
        sc = scale.astype(jnp.float32)[None, :]
        sh = bias.astype(jnp.float32)[None, :]
        per_row = False
    reason = _k.eligible(acc2.shape, act=act)
    go, cfg = _tier.should_dispatch(_k.OP_NAME,
                                    _k.shape_key_shapes(acc2.shape),
                                    "int32", guard_reason=reason)
    if go:
        out2 = _k.dequant_epilogue(acc2, sc, sh, per_row=per_row, act=act,
                                   config=cfg)
        return out2.reshape(acc.shape)
    bshape = [1] * acc.ndim
    bshape[channel_axis] = -1
    y = (acc.astype(jnp.float32) * scale.reshape(bshape)
         + bias.reshape(bshape))
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y


@register("_contrib_quantized_fc_int8")
def quantized_fc_int8(data, weight_q, out_scale, out_bias, *, num_hidden,
                      act_scale, act="identity", flatten=True):
    """int8 FullyConnected for the serving path.

    data f32 (quantized in-op with the static calibrated ``act_scale``),
    weight_q int8 (K, D), out_scale/out_bias f32 (K,) holding
    dequant * BN-affine and BN-shift + dequantized bias.
    """
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    xq = _quantize_static(x, act_scale)
    acc = lax.dot_general(xq, weight_q.astype(jnp.int8),
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return _epilogue(acc, out_scale, out_bias, acc.ndim - 1, act)


@register("_contrib_quantized_conv_int8")
def quantized_conv_int8(data, weight_q, out_scale, out_bias, *, kernel,
                        num_filter, act_scale, stride=None, dilate=None,
                        pad=None, act="identity"):
    """int8 NCHW Convolution for the serving path (groups=1 only — the
    rewrite guard enforces it). Same scale/bias contract as the FC op,
    per output channel (axis 1)."""
    n = len(kernel)
    stride = tuple(s if s else 1 for s in (stride or (1,) * n))
    dilate = tuple(d if d else 1 for d in (dilate or (1,) * n))
    padding = [(p, p) for p in (pad or (0,) * n)]
    fmt = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
           3: ("NCDHW", "OIDHW", "NCDHW")}[n]
    dn = lax.conv_dimension_numbers(data.shape, weight_q.shape, fmt)
    xq = _quantize_static(data, act_scale)
    acc = lax.conv_general_dilated(
        xq, weight_q.astype(jnp.int8), window_strides=stride,
        padding=padding, rhs_dilation=dilate, dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    return _epilogue(acc, out_scale, out_bias, 1, act)
