"""Elementwise / broadcast / scalar operators.

Parity targets: the reference's elemwise machinery (src/operator/mshadow_op.h
functor library, src/operator/tensor/elemwise_*.cc) — here each functor is a
jnp expression; XLA fuses chains of these into single kernels, replacing the
reference's hand-tuned Kernel<OP,xpu>::Launch machinery.

MXNet distinguishes ``elemwise_add`` (no broadcasting) from ``broadcast_add``;
XLA broadcasting subsumes both, so both names map to one fn.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias

# ---------------------------------------------------------------------------
# binary (broadcasting)
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: jnp.equal(a, b).astype(a.dtype),
    "not_equal": lambda a, b: jnp.not_equal(a, b).astype(a.dtype),
    "greater": lambda a, b: jnp.greater(a, b).astype(a.dtype),
    "greater_equal": lambda a, b: jnp.greater_equal(a, b).astype(a.dtype),
    "lesser": lambda a, b: jnp.less(a, b).astype(a.dtype),
    "lesser_equal": lambda a, b: jnp.less_equal(a, b).astype(a.dtype),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(a.dtype),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(a.dtype),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(a.dtype),
}

for _name, _f in _BINARY.items():
    def _make(f):
        def op(lhs, rhs):
            return f(lhs, rhs)
        return op
    _fn = _make(_f)
    _fn.__name__ = "broadcast_" + _name
    register("broadcast_" + _name)(_fn)
    alias("broadcast_" + _name, "elemwise_" + _name, "_" + _name)

alias("broadcast_add", "broadcast_plus", "_plus")
alias("broadcast_sub", "broadcast_minus", "_minus")
alias("broadcast_div", "_true_divide")
alias("broadcast_maximum", "maximum")
alias("broadcast_minimum", "minimum")
alias("broadcast_power", "pow")


# ---------------------------------------------------------------------------
# binary with scalar
# ---------------------------------------------------------------------------

def _scalar_op(name, f, reverse_f=None):
    def op(data, *, scalar=1.0):
        return f(data, jnp.asarray(scalar, dtype=data.dtype))
    op.__name__ = name
    register(name)(op)
    if reverse_f is not None:
        def rop(data, *, scalar=1.0):
            return reverse_f(jnp.asarray(scalar, dtype=data.dtype), data)
        rop.__name__ = "_r" + name.lstrip("_")
        register("_r" + name.lstrip("_"))(rop)


_scalar_op("_plus_scalar", jnp.add)
_scalar_op("_minus_scalar", jnp.subtract)
_scalar_op("_rminus_scalar", lambda a, s: jnp.subtract(s, a))
_scalar_op("_mul_scalar", jnp.multiply)
_scalar_op("_div_scalar", jnp.divide)
_scalar_op("_rdiv_scalar", lambda a, s: jnp.divide(s, a))
_scalar_op("_mod_scalar", jnp.mod)
_scalar_op("_rmod_scalar", lambda a, s: jnp.mod(s, a))
_scalar_op("_power_scalar", jnp.power)
_scalar_op("_rpower_scalar", lambda a, s: jnp.power(s, a))
_scalar_op("_maximum_scalar", jnp.maximum)
_scalar_op("_minimum_scalar", jnp.minimum)
_scalar_op("_equal_scalar", lambda a, s: jnp.equal(a, s).astype(a.dtype))
_scalar_op("_not_equal_scalar", lambda a, s: jnp.not_equal(a, s).astype(a.dtype))
_scalar_op("_greater_scalar", lambda a, s: jnp.greater(a, s).astype(a.dtype))
_scalar_op("_greater_equal_scalar", lambda a, s: jnp.greater_equal(a, s).astype(a.dtype))
_scalar_op("_lesser_scalar", lambda a, s: jnp.less(a, s).astype(a.dtype))
_scalar_op("_lesser_equal_scalar", lambda a, s: jnp.less_equal(a, s).astype(a.dtype))


# ---------------------------------------------------------------------------
# unary math (mshadow_op.h:59-195 functors)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "round": jnp.round,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}

for _name, _f in _UNARY.items():
    def _make_u(f):
        def op(data):
            return f(data)
        return op
    _fn = _make_u(_f)
    _fn.__name__ = _name
    register(_name)(_fn)

alias("negative", "_np_negative")
alias("relu", "_relu")


@register("clip")
def clip(data, *, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("_copy")
def _copy(data):
    return jnp.asarray(data)


alias("_copy", "identity")


@register("BlockGrad")
def block_grad(data):
    return jax.lax.stop_gradient(data)


alias("BlockGrad", "stop_gradient")


def _make_loss_core(data, grad_scale, normalization):
    @jax.custom_vjp
    def f(x):
        return x * 1.0

    def fwd(x):
        return x * 1.0, x.shape

    def bwd(shape, g):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / shape[0]
        elif normalization == "valid":
            scale = scale / max(1, int(jnp.prod(jnp.asarray(shape))))
        return (jnp.ones(shape, g.dtype) * scale,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("make_loss")
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Head-gradient source (reference src/operator/make_loss-inl.h): backward
    seeds grad_scale regardless of incoming cotangent."""
    return _make_loss_core(data, grad_scale, normalization)


@register("where")
def where(condition, x, y):
    """Elementwise select; a 1-D condition over N-D operands selects whole
    ROWS along axis 0 (reference control_flow_op.h WhereOpShape: csr/1-D
    condition of length x.shape[0])."""
    cond = condition.astype(bool)
    xshape = jnp.shape(x)
    if cond.ndim == 1 and len(xshape) > 1:
        if cond.shape[0] != xshape[0]:
            raise ValueError(
                "where: 1-D condition length %d must equal x.shape[0]=%d "
                "(reference control_flow_op.h WhereOpShape)"
                % (cond.shape[0], xshape[0]))
        cond = cond.reshape((-1,) + (1,) * (len(xshape) - 1))
    return jnp.where(cond, x, y)


@register("Cast")
def cast(data, *, dtype="float32"):
    from ..base import normalize_dtype
    return data.astype(normalize_dtype(dtype))


alias("Cast", "cast")


def _cast_dtypes(in_dtypes, params):
    import numpy as _np2
    from ..base import normalize_dtype
    return list(in_dtypes), [_np2.dtype(normalize_dtype(
        params.get("dtype", "float32")))]


from .registry import set_op_meta as _set_op_meta  # noqa: E402
_set_op_meta("Cast", dtype_hook=_cast_dtypes)


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("add_n")
def add_n(*args):
    """Sum of any number of input arrays, elementwise (parity:
    src/operator/tensor/elemwise_sum.cc add_n/ElementWiseSum). XLA folds
    the chain into one fused reduction; no pairwise temp like the
    reference's in-place accumulation needs."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


alias("add_n", "ElementWiseSum")
