"""Legacy operator names + the remaining small op tail.

Parity: every name here is registered in the reference's operator table
and reachable from old scripts/JSON (legacy capitalized elemwise names
from the pre-0.9 era — src/operator/tensor/elemwise_binary_op_basic.cc
add_alias chains; random-sampling surface names — random/sample_op.cc;
deprecated layer names — batch_norm_v1.cc, convolution_v1.cc,
pooling_v1.cc, softmax.cc).

Deliberately NOT registered (documented refusals):
* ``_Native`` / ``_NDArray`` — C-callback op bridges of the 0.x C API;
  the Python CustomOp path (ops/custom_op.py) is the supported analog.
* ``_CrossDeviceCopy`` — explicit D2D copy node; XLA/GSPMD moves data.
* ``_sg_mkldnn_conv`` / ``_trt_op`` — backend-fused subgraph nodes of
  MKLDNN/TensorRT; the subgraph framework + AOT serving fill the role.
* ``_cond``/``_while_loop``/``_foreach`` — not registry entries, but
  fully supported: symbol/contrib.py builds them as per-instance
  subgraph nodes (lax lowering, JSON serde with embedded subgraphs),
  and ndarray/contrib.py provides the functional eager/hybrid forms.
* ``IdentityAttachKLSparseReg`` — sparse-activation KL regularizer tied
  to the v0.x executor's aux-state update hooks; no modern consumer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from .. import random as _random

# ---------------------------------------------------------------- aliases
# legacy capitalized elemwise family
for _legacy, _new in {
    "_Plus": "elemwise_add", "_Minus": "elemwise_sub",
    "_Mul": "elemwise_mul", "_Div": "elemwise_div",
    "_Mod": "_mod", "_Power": "_power",
    "_Maximum": "_maximum", "_Minimum": "_minimum",
    "_Hypot": "_hypot",
    "_Equal": "_equal", "_Not_Equal": "_not_equal",
    "_Greater": "_greater", "_Greater_Equal": "_greater_equal",
    "_Lesser": "_lesser", "_Lesser_Equal": "_lesser_equal",
    "_Logical_And": "_logical_and", "_Logical_Or": "_logical_or",
    "_Logical_Xor": "_logical_xor",
    "_PlusScalar": "_plus_scalar", "_MinusScalar": "_minus_scalar",
    "_RMinusScalar": "_rminus_scalar", "_MulScalar": "_mul_scalar",
    "_DivScalar": "_div_scalar", "_RDivScalar": "_rdiv_scalar",
    "_ModScalar": "_mod_scalar", "_RModScalar": "_rmod_scalar",
    "_PowerScalar": "_power_scalar", "_RPowerScalar": "_rpower_scalar",
    "_MaximumScalar": "_maximum_scalar",
    "_MinimumScalar": "_minimum_scalar",
    "_EqualScalar": "_equal_scalar",
    "_NotEqualScalar": "_not_equal_scalar",
    "_GreaterScalar": "_greater_scalar",
    "_GreaterEqualScalar": "_greater_equal_scalar",
    "_LesserScalar": "_lesser_scalar",
    "_LesserEqualScalar": "_lesser_equal_scalar",
    # grad accumulation node (elemwise_sum.cc _grad_add)
    "_grad_add": "elemwise_add",
    # deprecated layer names
    "BatchNorm_v1": "BatchNorm", "CuDNNBatchNorm": "BatchNorm",
    "Convolution_v1": "Convolution", "Pooling_v1": "Pooling",
    "Softmax": "SoftmaxOutput",   # softmax.cc: deprecated SoftmaxOutput
    "crop": "Crop",
    # random-surface names (random/sample_op.cc aliases)
    "uniform": "_random_uniform", "random_uniform": "_random_uniform",
    "normal": "_random_normal", "random_normal": "_random_normal",
    "random_gamma": "_random_gamma",
    "random_exponential": "_random_exponential",
    "random_poisson": "_random_poisson",
    "random_negative_binomial": "_random_negative_binomial",
    "random_generalized_negative_binomial":
        "_random_generalized_negative_binomial",
    "random_randint": "_random_randint",
    "sample_multinomial": "_sample_multinomial",
    "shuffle": "_shuffle",
    # contrib spellings
    "_contrib_CTCLoss": "CTCLoss", "_contrib_ctc_loss": "CTCLoss",
    "_contrib_box_non_maximum_suppression": "_contrib_box_nms",
    "_contrib_group_adagrad_update": "group_adagrad_update",
    "_zeros_without_dtype": "_zeros",
}.items():
    alias(_new, _legacy)


# ------------------------------------------------- missing scalar logicals
@register("_logical_and_scalar")
def logical_and_scalar(data, *, scalar):
    return ((data != 0) & (scalar != 0)).astype(data.dtype)


@register("_logical_or_scalar")
def logical_or_scalar(data, *, scalar):
    return ((data != 0) | (scalar != 0)).astype(data.dtype)


@register("_logical_xor_scalar")
def logical_xor_scalar(data, *, scalar):
    return ((data != 0) ^ (scalar != 0)).astype(data.dtype)


@register("_hypot_scalar")
def hypot_scalar(data, *, scalar):
    return jnp.hypot(data, jnp.asarray(scalar, data.dtype))


# --------------------------------------------------------- small real ops
@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    """clip(alpha*x + beta, 0, 1) (elemwise_unary_op_basic.cc)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("_histogram", num_outputs=2)
def histogram(data, bins=None, *, bin_cnt=None, range=None):
    """(counts, edges) (src/operator/tensor/histogram.cc): either an
    explicit edges array input or (bin_cnt, range)."""
    if bin_cnt is not None:
        if range is None or len(tuple(range)) != 2:
            from ..base import MXNetError
            raise MXNetError(
                "_histogram: bin_cnt requires range=(min, max) "
                "(reference histogram.cc HistogramParam)")
        cnt, edges = jnp.histogram(data.ravel(), bins=int(bin_cnt),
                                   range=tuple(range))
    else:
        cnt, edges = jnp.histogram(data.ravel(), bins=bins)
    return cnt, edges


@register("_ravel_multi_index")
def ravel_multi_index(data, *, shape):
    """(ndim, N) coords -> flat indices (tensor/ravel.cc)."""
    coords = tuple(data[i].astype(jnp.int32) for i in range(data.shape[0]))
    out = jnp.ravel_multi_index(coords, tuple(shape), mode="clip")
    return out.astype(data.dtype)


@register("_unravel_index")
def unravel_index(data, *, shape):
    """flat indices (N,) -> (ndim, N) coords (tensor/ravel.cc)."""
    coords = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(coords).astype(data.dtype)


@register("_identity_with_attr_like_rhs")
def identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs carrying rhs's storage attr (used by the sparse
    optimizer passes); values are lhs verbatim."""
    return lhs * 1.0


@register("_rnn_param_concat")
def rnn_param_concat(*data, dim=0):
    """Concat specialized for RNN parameter flattening (rnn.cc)."""
    return jnp.concatenate(data, axis=dim)


@register("_square_sum")
def square_sum(data, *, axis=None, keepdims=False, exclude=False):
    """sum(x^2) (square_sum.cc — the rsp-optimized fused form; one XLA
    fusion here)."""
    ax = None if axis is None else tuple(axis) if isinstance(
        axis, (tuple, list)) else (axis,)
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


@register("cast_storage")
def cast_storage_op(data, *, stype):
    """Dense graph node: storage casting is an NDArray-layer concept
    (ndarray/sparse.py cast_storage does the real conversion); inside a
    compiled graph every tensor is dense, so this is identity."""
    return data * 1.0


@register("_sparse_retain")
def sparse_retain(data, indices):
    """Keep only the requested rows (sparse_retain.cc). Dense lowering:
    zero every row NOT selected — the rsp path lives on
    RowSparseNDArray.retain."""
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, jnp.zeros_like(data))


@register("_scatter_plus_scalar")
def scatter_plus_scalar(data, *, scalar):
    """Sparse-aware scalar add (elemwise_scatter_op.cc: touches only
    stored values of an rsp/csr input; dense math is identical)."""
    return data + scalar


@register("_scatter_minus_scalar")
def scatter_minus_scalar(data, *, scalar):
    return data - scalar


@register("_scatter_elemwise_div")
def scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


@register("_scatter_set_nd")
def scatter_set_nd(lhs, rhs, indices, *, shape=None):
    """Write rhs into lhs at gather_nd-style indices
    (tensor/indexing_op.cc scatter_set_nd)."""
    idx = tuple(indices[i].astype(jnp.int32)
                for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


# ------------------------------------------------ missing sample_* family
def _sample_shape(params0, shape):
    shape = tuple(shape) if shape else ()
    return params0.shape + shape


@register("_sample_exponential", is_random=True)
def sample_exponential(lam, *, shape=None, dtype="float32"):
    out = _sample_shape(lam, shape)
    k = _random.next_key()
    e = jax.random.exponential(k, out).astype(dtype)
    return e / lam.reshape(lam.shape + (1,) * (len(out) - lam.ndim))


@register("_sample_poisson", is_random=True)
def sample_poisson(lam, *, shape=None, dtype="float32"):
    out = _sample_shape(lam, shape)
    k = _random.next_key()
    lam_b = lam.reshape(lam.shape + (1,) * (len(out) - lam.ndim))
    return jax.random.poisson(k, lam_b, out).astype(dtype)


@register("_sample_negative_binomial", is_random=True)
def sample_negative_binomial(k, p, *, shape=None, dtype="float32"):
    """NB(k, p) = Poisson(Gamma(k, (1-p)/p)) (same mixture the reference
    sampler uses)."""
    out = _sample_shape(k, shape)
    kk = _random.next_key()
    k_b = k.reshape(k.shape + (1,) * (len(out) - k.ndim))
    p_b = p.reshape(p.shape + (1,) * (len(out) - p.ndim))
    g = jax.random.gamma(kk, k_b, out) * (1.0 - p_b) / p_b
    return jax.random.poisson(_random.next_key(), g).astype(dtype)


@register("_sample_generalized_negative_binomial", is_random=True)
def sample_gnb(mu, alpha, *, shape=None, dtype="float32"):
    """GNB(mu, alpha): Poisson with Gamma(1/alpha, mu*alpha) rate."""
    out = _sample_shape(mu, shape)
    kk = _random.next_key()
    mu_b = mu.reshape(mu.shape + (1,) * (len(out) - mu.ndim))
    a_b = alpha.reshape(alpha.shape + (1,) * (len(out) - alpha.ndim))
    g = jax.random.gamma(kk, 1.0 / a_b, out) * mu_b * a_b
    return jax.random.poisson(_random.next_key(), g).astype(dtype)


@register("_sparse_adagrad_update", num_outputs=2)
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Dense lowering of the rsp adagrad kernel (optimizer_op.cc); the
    truly-lazy row path lives in optimizer.AdaGrad's rsp branch."""
    from .optimizer_ops import adagrad_update
    return adagrad_update(weight, grad, history, lr=lr, epsilon=epsilon,
                          wd=wd, rescale_grad=rescale_grad,
                          clip_gradient=clip_gradient)
