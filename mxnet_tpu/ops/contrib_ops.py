"""Contrib / detection / spatial operators (parity: src/operator/contrib/ —
ROIAlign roi_align.cc, MultiBox multibox_*.cc (SSD), box_nms bounding_box.cc,
boolean_mask, index_copy, fft; legacy spatial ops roi_pooling,
bilinear_sampler, spatial_transformer, grid_generator, svm_output).

All are pure-XLA lowerings; gather/dynamic-slice based kernels keep static
shapes (SURVEY.md §7 hard-part 1) by padding/masking instead of producing
data-dependent sizes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


# ---------------------------------------------------------------------------
# ROI pooling / align
# ---------------------------------------------------------------------------

@register("ROIPooling")
def roi_pooling(data, rois, *, pooled_size, spatial_scale):
    """Max-pool regions of interest (reference src/operator/roi_pooling.cc).

    data: (N, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2]."""
    n, c, h, w = data.shape
    ph, pw = pooled_size

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[bi]  # (C, H, W)
        ys = jnp.arange(h)[None, :]
        xs = jnp.arange(w)[None, :]
        out = jnp.zeros((c, ph, pw), data.dtype)
        hb = rh / ph
        wb = rw / pw
        rows = []
        for py in range(ph):
            cols = []
            y_lo = y1 + jnp.floor(py * hb).astype(jnp.int32)
            y_hi = y1 + jnp.ceil((py + 1) * hb).astype(jnp.int32)
            ymask = (jnp.arange(h) >= y_lo) & (jnp.arange(h) < jnp.maximum(
                y_hi, y_lo + 1)) & (jnp.arange(h) <= y2)
            for px in range(pw):
                x_lo = x1 + jnp.floor(px * wb).astype(jnp.int32)
                x_hi = x1 + jnp.ceil((px + 1) * wb).astype(jnp.int32)
                xmask = (jnp.arange(w) >= x_lo) & \
                    (jnp.arange(w) < jnp.maximum(x_hi, x_lo + 1)) & \
                    (jnp.arange(w) <= x2)
                m = ymask[:, None] & xmask[None, :]
                cell = jnp.where(m[None], img, -jnp.inf)
                cols.append(jnp.max(cell, axis=(1, 2)))
            rows.append(jnp.stack(cols, axis=-1))
        out = jnp.stack(rows, axis=-2)  # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign")
def roi_align(data, rois, *, pooled_size, spatial_scale, sample_ratio=-1,
              position_sensitive=False):
    """Bilinear ROI align (reference contrib/roi_align.cc)."""
    n, c, h, w = data.shape
    ph, pw = pooled_size
    sr = 2 if sample_ratio <= 0 else sample_ratio

    def bilinear(img, y, x):
        y = jnp.clip(y, 0.0, h - 1.0)
        x = jnp.clip(x, 0.0, w - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        ly, lx = y - y0, x - x0
        v = (img[:, y0, x0] * (1 - ly) * (1 - lx)
             + img[:, y1, x0] * ly * (1 - lx)
             + img[:, y0, x1] * (1 - ly) * lx
             + img[:, y1, x1] * ly * lx)
        return v

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale
        y1 = roi[2] * spatial_scale
        x2 = roi[3] * spatial_scale
        y2 = roi[4] * spatial_scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        img = data[bi]
        bin_h = rh / ph
        bin_w = rw / pw
        out = []
        for py in range(ph):
            row = []
            for px in range(pw):
                acc = 0.0
                for iy in range(sr):
                    for ix in range(sr):
                        y = y1 + (py + (iy + 0.5) / sr) * bin_h
                        x = x1 + (px + (ix + 0.5) / sr) * bin_w
                        acc = acc + bilinear(img, y, x)
                row.append(acc / (sr * sr))
            out.append(jnp.stack(row, axis=-1))
        return jnp.stack(out, axis=-2)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# Spatial transformer family (legacy ops)
# ---------------------------------------------------------------------------

@register("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    """Affine/warp grid (reference spatial ops). affine: data (N, 6)."""
    th, tw = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, th)
        xs = jnp.linspace(-1.0, 1.0, tw)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, coords)  # (N, 2, HW)
        return out.reshape(n, 2, th, tw)
    # 'warp': data is (N, 2, H, W) flow field
    n, _, h, w = data.shape
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gx, gy = jnp.meshgrid(xs, ys)
    x = (data[:, 0] + gx) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
    y = (data[:, 1] + gy) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
    return jnp.stack([x, y], axis=1)


def _grid_sample(data, grid):
    """Bilinear sample data (N,C,H,W) at grid (N,2,Ho,Wo) in [-1,1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    lx, ly = gx - x0, gy - y0

    def gather(img, yy, xx):
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]  # (C, Ho, Wo)
        return jnp.where(valid[None], vals, 0.0)

    def one(img, x0_, y0_, lx_, ly_):
        v00 = gather(img, y0_, x0_)
        v01 = gather(img, y0_, x0_ + 1)
        v10 = gather(img, y0_ + 1, x0_)
        v11 = gather(img, y0_ + 1, x0_ + 1)
        return (v00 * (1 - ly_) * (1 - lx_) + v01 * (1 - ly_) * lx_
                + v10 * ly_ * (1 - lx_) + v11 * ly_ * lx_)

    return jax.vmap(one)(data, x0, y0, lx, ly)


@register("BilinearSampler")
def bilinear_sampler(data, grid):
    """Sample data at grid locations (reference bilinear_sampler.cc)."""
    return _grid_sample(data, grid)


@register("SpatialTransformer")
def spatial_transformer(data, loc, *, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear"):
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=tuple(target_shape))
    return _grid_sample(data, grid)


# ---------------------------------------------------------------------------
# Detection: multibox (SSD), box_nms
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior")
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes per feature-map cell (reference multibox_prior.cc)."""
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    anchors = []
    # reference layout: (sizes[0],r) for all ratios, then (s,ratios[0])
    specs = [(sizes[0], r) for r in ratios] + \
            [(s, ratios[0]) for s in sizes[1:]]
    for s, r in specs:
        sr = jnp.sqrt(r)
        bw = s * sr / 2
        bh = s / sr / 2
        anchors.append(jnp.stack(
            [cx - bw, cy - bh, cx + bw, cy + bh], axis=-1))
    out = jnp.stack(anchors, axis=2).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _box_iou_corner(a, b):
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:4], b[..., None, :, 2:4])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


@register("_contrib_box_iou")
def box_iou(lhs, rhs, *, format="corner"):
    return _box_iou_corner(lhs, rhs)


@register("_contrib_box_nms")
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False,
            in_format="corner", out_format="corner", background_id=-1):
    """Greedy NMS with static shapes: suppressed entries become -1 rows
    (reference bounding_box.cc box_nms)."""
    single = data.ndim == 2
    if single:
        data = data[None]
    b, n, k = data.shape
    scores = data[..., score_index]
    boxes = data[..., coord_start:coord_start + 4]
    class_aware = id_index >= 0 and not force_suppress
    ids = data[..., id_index] if id_index >= 0 else jnp.zeros((b, n))

    def one(sample_scores, sample_boxes, sample_ids, sample_data):
        order = jnp.argsort(-sample_scores)
        sboxes = sample_boxes[order]
        sscores = sample_scores[order]
        sdata = sample_data[order]
        sids = sample_ids[order]
        iou = _box_iou_corner(sboxes, sboxes)
        keep = sscores > valid_thresh

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i)
            if class_aware:
                sup = sup & (sids == sids[i])
            return jnp.where(keep[i], keep & ~sup, keep)
        keep = lax.fori_loop(0, n, body, keep)
        if topk > 0:
            keep = keep & (jnp.cumsum(keep.astype(jnp.int32)) <= topk)
        return jnp.where(keep[:, None], sdata, -jnp.ones_like(sdata))

    out = jax.vmap(one)(scores, boxes, ids, data)
    return out[0] if single else out


@register("_contrib_MultiBoxDetection")
def multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1):
    """Decode SSD predictions to detections (reference
    multibox_detection.cc): cls_prob (B, num_cls, A), loc_pred (B, A*4),
    anchor (1, A, 4) -> (B, A, 6) [cls_id, score, x1, y1, x2, y2]."""
    b, num_cls, a = cls_prob.shape
    loc = loc_pred.reshape(b, a, 4)
    anc = anchor.reshape(a, 4)
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    cx = loc[..., 0] * variances[0] * aw + acx
    cy = loc[..., 1] * variances[1] * ah + acy
    bw = jnp.exp(loc[..., 2] * variances[2]) * aw / 2
    bh = jnp.exp(loc[..., 3] * variances[3]) * ah / 2
    x1, y1, x2, y2 = cx - bw, cy - bh, cx + bw, cy + bh
    if clip:
        x1, y1 = jnp.clip(x1, 0, 1), jnp.clip(y1, 0, 1)
        x2, y2 = jnp.clip(x2, 0, 1), jnp.clip(y2, 0, 1)
    # best non-background class per anchor
    fg = cls_prob[:, 1:] if background_id == 0 else cls_prob
    cls_id = jnp.argmax(fg, axis=1).astype(jnp.float32)
    score = jnp.max(fg, axis=1)
    valid = score > threshold
    cls_id = jnp.where(valid, cls_id, -1.0)
    det = jnp.stack([cls_id, score, x1, y1, x2, y2], axis=-1)
    return box_nms(det, overlap_thresh=nms_threshold, valid_thresh=threshold,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


# ---------------------------------------------------------------------------
# boolean_mask / index_copy / SVM / fft
# ---------------------------------------------------------------------------

@register("_contrib_boolean_mask")
def boolean_mask(data, index, *, axis=0):
    """Static-shape variant: masked-out rows are zeroed and compacted to the
    front; the count of kept rows is data-dependent, so on TPU the output
    keeps full length (XLA needs static shapes; reference returns a
    dynamically-sized array on CPU/GPU)."""
    mask = index.astype(bool)
    n = data.shape[axis]
    order = jnp.argsort(~mask, stable=True)  # kept rows first
    gathered = jnp.take(data, order, axis=axis)
    kept = jnp.sort(mask)[::-1]
    shape = [1] * data.ndim
    shape[axis] = n
    return gathered * kept.reshape(shape).astype(data.dtype)


@register("_contrib_index_copy")
def index_copy(old_tensor, index_vector, new_tensor):
    idx = index_vector.astype(jnp.int32)
    return old_tensor.at[idx].set(new_tensor)


@register("SVMOutput")
def svm_output(data, label=None, *, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Hinge-loss head (reference svm_output.cc): forward is identity; the
    custom vjp applies the SVM gradient."""
    if label is None:
        return data * 1.0

    @jax.custom_vjp
    def core(d, lab):
        return d * 1.0

    def fwd(d, lab):
        return d * 1.0, (d, lab)

    def bwd(res, g):
        d, lab = res
        n, c = d.shape[0], d.shape[-1]
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), c, dtype=d.dtype)
        sign = 2.0 * onehot - 1.0  # +1 for true class, -1 otherwise
        violate = (margin - sign * d) > 0
        if use_linear:
            grad = jnp.where(violate, -sign, 0.0)
        else:
            grad = jnp.where(violate, -2.0 * (margin - sign * d) * sign, 0.0)
        return (regularization_coefficient * grad, None)

    core.defvjp(fwd, bwd)
    return core(data, label)


@register("_contrib_fft")
def contrib_fft(data, *, compute_size=128):
    """FFT over the last axis, packed [real, imag] interleaved as the
    reference does (contrib/fft.cc): output last dim is 2x input."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    packed = jnp.stack([out.real, out.imag], axis=-1)
    return packed.reshape(*data.shape[:-1], data.shape[-1] * 2) \
        .astype(jnp.float32)


@register("_contrib_ifft")
def contrib_ifft(data, *, compute_size=128):
    n = data.shape[-1] // 2
    unpacked = data.reshape(*data.shape[:-1], n, 2)
    comp = unpacked[..., 0] + 1j * unpacked[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * n


@register("_contrib_count_sketch")
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """Count-sketch projection (reference contrib/count_sketch.cc)."""
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    contrib = data * ss[None, :]
    out = jnp.zeros(data.shape[:-1] + (int(out_dim),), data.dtype)
    return out.at[..., hh].add(contrib)


@register("_contrib_getnnz")
def getnnz(data, *, axis=None):
    """NONZERO count of a dense array. The reference op
    (contrib/nnz.cc:172) counts a CSR's STORED values (explicit zeros
    included) — that semantics needs storage metadata, so it lives on the
    sparse-aware eager wrapper ``mx.nd.contrib.getnnz``; this registry op
    is its dense fallback."""
    from ..base import index_dtype
    if axis is None:
        return jnp.sum(data != 0).astype(index_dtype())
    return jnp.sum(data != 0, axis=axis).astype(index_dtype())


@register("_contrib_MultiBoxTarget", num_outputs=3)
def multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference multibox_target.cc): bipartite-match
    each ground truth to its best anchor, then threshold-match the rest;
    matched anchors get encoded box offsets + class id+1, the rest are
    background — optionally hard-negative-mined by classification
    confidence. Returns (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N)).

    Static-shape design: the reference's per-sample greedy loops become a
    fori_loop bipartite pass + vectorized threshold matching under vmap.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    m = label.shape[1]
    v0, v1, v2, v3 = (float(v) for v in variances)

    a_w = anchors[:, 2] - anchors[:, 0]
    a_h = anchors[:, 3] - anchors[:, 1]
    a_cx = (anchors[:, 0] + anchors[:, 2]) / 2
    a_cy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(lab, conf):
        valid = lab[:, 0] >= 0                       # (M,)
        gt = lab[:, 1:5]
        iou = _box_iou_corner(anchors, gt)           # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # bipartite: best-first, each gt claims one anchor; claimed gts
        # leave the pool so every gt gets its guaranteed match
        def bi_body(_, carry):
            match, taken, gt_done = carry            # match: (N,) gt idx
            masked = jnp.where(taken[:, None] | gt_done[None, :], -2.0,
                               iou)
            best_per_gt = jnp.max(masked, axis=0)    # (M,)
            g = jnp.argmax(jnp.where(valid & ~gt_done
                                     & (best_per_gt > -2.0),
                                     best_per_gt, -3.0))
            a = jnp.argmax(masked[:, g])
            # reference floor (multibox_target.cc:116): a gt overlapping
            # NO anchor is left unmatched rather than grabbing anchor 0
            ok = valid[g] & ~gt_done[g] & (masked[a, g] > 1e-6)
            match = jnp.where(ok & (jnp.arange(n) == a), g, match)
            taken = taken | (ok & (jnp.arange(n) == a))
            gt_done = gt_done | (ok & (jnp.arange(m) == g))
            return match, taken, gt_done

        match0 = jnp.full((n,), -1, jnp.int32)
        match, taken, _ = lax.fori_loop(
            0, m, bi_body,
            (match0, jnp.zeros((n,), bool), jnp.zeros((m,), bool)))

        # threshold matching for the rest (skipped entirely when
        # overlap_threshold <= 0: bipartite-only, multibox_target.cc:170)
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        if overlap_threshold > 0:
            thr_ok = (~taken) & (best_iou > overlap_threshold)
            match = jnp.where(thr_ok, best_gt, match)
        matched = match >= 0
        midx = jnp.clip(match, 0, m - 1)

        g_box = gt[midx]                              # (N, 4)
        g_w = jnp.maximum(g_box[:, 2] - g_box[:, 0], 1e-12)
        g_h = jnp.maximum(g_box[:, 3] - g_box[:, 1], 1e-12)
        g_cx = (g_box[:, 0] + g_box[:, 2]) / 2
        g_cy = (g_box[:, 1] + g_box[:, 3]) / 2
        tx = (g_cx - a_cx) / jnp.maximum(a_w, 1e-12) / v0
        ty = (g_cy - a_cy) / jnp.maximum(a_h, 1e-12) / v1
        tw = jnp.log(jnp.maximum(g_w / jnp.maximum(a_w, 1e-12), 1e-12)) / v2
        th = jnp.log(jnp.maximum(g_h / jnp.maximum(a_h, 1e-12), 1e-12)) / v3
        loc = jnp.stack([tx, ty, tw, th], axis=1)     # (N, 4)
        loc_t = jnp.where(matched[:, None], loc, 0.0).reshape(-1)
        loc_m = jnp.where(matched[:, None],
                          jnp.ones((n, 4)), 0.0).reshape(-1)

        cls_t = jnp.where(matched, lab[midx, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard-negative mining, exact reference semantics
            # (multibox_target.cc:180-239): candidates are unmatched
            # anchors whose best IoU is BELOW negative_mining_thresh
            # (moderate-IoU anchors stay don't-care); hardness is the
            # softmax BACKGROUND probability, ascending; quota =
            # min(ratio * num_pos, num_anchors - num_pos); the rest of
            # the unmatched anchors are ignored.
            bg_prob = jax.nn.softmax(conf, axis=0)[0]     # (N,)
            num_pos = jnp.sum(matched)
            quota = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                jnp.int32(minimum_negative_samples))
            quota = jnp.minimum(quota, n - num_pos)
            is_cand = ~matched & (best_iou < negative_mining_thresh)
            quota = jnp.minimum(quota, jnp.sum(is_cand))
            order = jnp.argsort(jnp.where(is_cand, bg_prob, jnp.inf))
            rank = jnp.empty_like(order).at[order].set(jnp.arange(n))
            keep_neg = is_cand & (rank < quota)
            cls_t = jnp.where(~matched,
                              jnp.where(keep_neg, 0.0,
                                        float(ignore_label)), cls_t)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# bipartite matching (SSD/rcnn target assignment)
# ---------------------------------------------------------------------------

@register("_contrib_bipartite_matching", num_outputs=2)
def bipartite_matching(data, *, threshold, is_ascend=False, topk=-1):
    """Greedy bipartite matching on a score matrix [..., N, M] (parity:
    src/operator/contrib/bounding_box.cc:154 / bounding_box-inl.h:728-760).

    Returns (row_match, col_match): row_match[..., i] = matched column of
    row i (-1 if unmatched), col_match[..., j] = matched row of column j.

    TPU-native shape: one argsort of the flattened N*M scores per batch
    element, then a lax.fori_loop greedy walk with row/column markers —
    sequential like the reference's kernel (the walk is inherently
    ordered), but O(NM) scalar steps on sorted data instead of host code,
    and vmapped over the batch.
    """
    dshape = data.shape
    nrow, ncol = dshape[-2], dshape[-1]
    flat = data.reshape((-1, nrow * ncol))
    key = flat if is_ascend else -flat
    order = jnp.argsort(key, axis=1)

    def one(scores, idx):
        sorted_scores = scores[idx]
        good = (sorted_scores < threshold) if is_ascend \
            else (sorted_scores > threshold)
        # the walk stops at the first bad score (sorted => all later ones
        # are bad too): a prefix-AND turns the reference's `break` into a
        # mask the loop can consume without data-dependent control flow
        good = jnp.cumprod(good.astype(jnp.int32)) == 1

        def body(j, st):
            rmark, cmark, count = st
            ij = idx[j]
            r, c = ij // ncol, ij % ncol
            free = (rmark[r] == -1) & (cmark[c] == -1)
            # reference stops AFTER the assignment that exceeds topk
            # (bounding_box-inl.h:748-752): emulate by refusing matches
            # once count > topk
            under = (count <= topk) if topk > 0 else True
            take = free & good[j] & under
            rmark = rmark.at[r].set(jnp.where(take, c, rmark[r]))
            cmark = cmark.at[c].set(jnp.where(take, r, cmark[c]))
            return rmark, cmark, count + take.astype(jnp.int32)

        rmark = jnp.full((nrow,), -1, data.dtype)
        cmark = jnp.full((ncol,), -1, data.dtype)
        rmark, cmark, _ = lax.fori_loop(
            0, nrow * ncol, body, (rmark, cmark, jnp.int32(0)))
        return rmark, cmark

    rm, cm = jax.vmap(one)(flat, order)
    return (rm.reshape(dshape[:-1]),
            cm.reshape(dshape[:-2] + (ncol,)))


alias("_contrib_bipartite_matching", "bipartite_matching")
