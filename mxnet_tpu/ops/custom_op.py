"""The ``Custom`` operator: runs user Python (mx.operator.CustomOp) inside
any execution mode.

Reference: ``src/operator/custom/custom-inl.h:50-163`` — the reference
pushes custom-op callbacks onto a dedicated worker thread so Python never
blocks the engine. The XLA-native equivalent is ``jax.pure_callback``: the
compiled program escapes to host for exactly this op, and tracing uses the
Prop's declared shapes/dtypes instead of running Python. Gradients flow
through a ``jax.custom_vjp`` whose backward is a host callback into
``CustomOp.backward`` — so custom ops work eagerly, under hybridize, in
the symbolic executor, and inside the fused train step, with autograd.

Statefulness: the reference gives each executor its own operator instance,
so a forward may stash intermediates for its backward. Here every
*execution* of the forward callback creates a fresh instance and returns a
token (an extra int32 output); the token rides the custom_vjp residuals
into the backward callback, which pops the instance from a bounded live
table. Interleaved forwards of the same op therefore never share state.
Eager non-recording calls bypass the callback machinery entirely and run
the operator directly.
"""
from __future__ import annotations

import collections
import itertools
import threading

import numpy as _np

from .registry import register

# token -> operator instance awaiting its backward. Bounded: a forward
# whose backward never runs (inference under record, abandoned graphs)
# must not pin its stashed state forever.
_LIVE_CAP = 256
_LIVE = collections.OrderedDict()
_LIVE_LOCK = threading.Lock()
_TOKENS = itertools.count(1)


def _custom_num_outputs(params):
    from .. import operator as _operator
    prop = _operator.make_prop(
        params["op_type"], {k: v for k, v in params.items()
                            if k not in ("op_type", "_training")})
    return len(prop.list_outputs())


def _to_nd(x):
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp
    return NDArray(jnp.asarray(_np.asarray(x)))


def _new_operator(op_type, kwargs, sig):
    from .. import operator as _operator
    from ..context import current_context
    prop = _operator.make_prop(op_type, kwargs)
    return prop.create_operator(current_context(),
                                [list(s) for s, _ in sig],
                                [d for _, d in sig])


def _stash(op):
    with _LIVE_LOCK:
        token = next(_TOKENS) & 0x7FFFFFFF
        _LIVE[token] = op
        while len(_LIVE) > _LIVE_CAP:
            _LIVE.popitem(last=False)
    return token


def _take(token, op_type, kwargs, sig):
    with _LIVE_LOCK:
        op = _LIVE.pop(int(token), None)
    if op is None:
        # evicted or replayed: fall back to a fresh (stateless) instance
        op = _new_operator(op_type, kwargs, sig)
    return op


@register("Custom", num_outputs=_custom_num_outputs)
def custom(*inputs, op_type, _training=False, **kwargs):
    """Dispatch to the registered CustomOpProp/CustomOp (reference
    ``mx.nd.Custom`` / ``mx.symbol.Custom``)."""
    import jax
    import jax.numpy as jnp
    from .. import operator as _operator

    prop = _operator.make_prop(op_type, kwargs)
    if prop.list_auxiliary_states():
        raise NotImplementedError(
            "custom ops with auxiliary states are not supported yet")
    in_shapes = [list(x.shape) for x in inputs]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [_np.dtype(x.dtype) for x in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    out_spec = tuple(jax.ShapeDtypeStruct(tuple(s), _np.dtype(t))
                     for s, t in zip(out_shapes, out_types))
    sig = tuple((tuple(x.shape), _np.dtype(x.dtype)) for x in inputs)
    n_in, n_out = len(inputs), len(out_spec)
    is_train = bool(_training)

    def run_forward(op, xs):
        in_data = [_to_nd(x) for x in xs]
        out_data = [_to_nd(_np.zeros(tuple(s.shape), s.dtype))
                    for s in out_spec]
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=in_data, out_data=out_data, aux=[])
        return tuple(_np.asarray(o.asnumpy(), s.dtype)
                     for o, s in zip(out_data, out_spec))

    # eager fast path: concrete inputs outside any trace run the operator
    # directly on-device NDArrays — no host round trip through callbacks
    if not any(isinstance(x, jax.core.Tracer) for x in inputs):
        op = _new_operator(op_type, kwargs, sig)
        outs = tuple(jnp.asarray(o)
                     for o in run_forward(op, [_np.asarray(x)
                                               for x in inputs]))
        return outs if n_out > 1 else outs[0]

    def fwd_cb(*xs):
        op = _new_operator(op_type, kwargs, sig)
        outs = run_forward(op, xs)
        return outs + (_np.int32(_stash(op)),)

    def bwd_cb(token, *args):
        op = _take(token, op_type, kwargs, sig)
        ins = [_to_nd(x) for x in args[:n_in]]
        outs = [_to_nd(x) for x in args[n_in:n_in + n_out]]
        cots = [_to_nd(x) for x in args[n_in + n_out:]]
        in_grad = [_to_nd(_np.zeros(tuple(s), d)) for s, d in sig]
        op.backward(req=["write"] * n_in, out_grad=cots, in_data=ins,
                    out_data=outs, in_grad=in_grad, aux=[])
        return tuple(_np.asarray(g.asnumpy(), d)
                     for g, (_, d) in zip(in_grad, sig))

    cb_spec = out_spec + (jax.ShapeDtypeStruct((), _np.int32),)

    @jax.custom_vjp
    def run(*ins):
        res = jax.pure_callback(fwd_cb, cb_spec, *ins)
        return tuple(res[:n_out])

    def run_fwd(*ins):
        res = jax.pure_callback(fwd_cb, cb_spec, *ins)
        outs = tuple(res[:n_out])
        return outs, (ins, outs, res[n_out])

    def run_bwd(res, cots):
        ins, outs, token = res
        grad_spec = tuple(jax.ShapeDtypeStruct(s, d) for s, d in sig)
        grads = jax.pure_callback(bwd_cb, grad_spec, token, *ins, *outs,
                                  *cots)
        # integer inputs take float0 cotangents
        fixed = []
        for g, (shape, dt) in zip(grads, sig):
            if _np.issubdtype(dt, _np.floating):
                fixed.append(g)
            else:
                fixed.append(_np.zeros(shape, jax.dtypes.float0))
        return tuple(fixed)

    run.defvjp(run_fwd, run_bwd)
    outs = run(*inputs)
    return outs if n_out > 1 else outs[0]
