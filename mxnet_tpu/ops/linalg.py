"""Linear-algebra operators (parity: src/operator/tensor/la_op.cc
NNVM_REGISTER_OP(_linalg_*) — gemm/gemm2/potrf/potri/trmm/trsm/
sumlogdiag/syrk/gelqf/syevd).

TPU-native: everything lowers through jnp.linalg / lax.linalg — batched
over leading dims by construction, differentiated by jax (the reference
hand-writes each backward kernel), and the triangular/Cholesky paths run
XLA's blocked algorithms on the MXU. The reference LAPACK flag surface
(lower, rightside, transpose, alpha) is honored.
"""
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


def _t(x):
    return jnp.swapaxes(x, -1, -2)


def _check_axis(axis):
    # reference axis selects which axis holds matrix rows for batched
    # operands; only the default (last-two-axes) layout is implemented —
    # refuse loudly rather than contract the wrong axes
    if axis != -2:
        raise NotImplementedError(
            "linalg gemm axis=%r unsupported: only the default axis=-2 "
            "(matrices in the trailing two dims) is implemented" % (axis,))


@register("_linalg_gemm")
def linalg_gemm(A, B, C, *, transpose_a=False, transpose_b=False,
                alpha=1.0, beta=1.0, axis=-2):
    """alpha * op(A) @ op(B) + beta * C (reference la_op.cc:37)."""
    _check_axis(axis)
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_gemm2")
def linalg_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    _check_axis(axis)
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_potrf")
def linalg_potrf(A):
    """Cholesky: A = L L^T, returns lower-triangular L (la_op.cc:187)."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri")
def linalg_potri(A):
    """Inverse of B from its Cholesky factor: given L (as produced by
    potrf), returns (L L^T)^-1 (la_op.cc:239)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = lax.linalg.triangular_solve(A, eye, left_side=True, lower=True)
    return jnp.matmul(_t(linv), linv)


@register("_linalg_trmm")
def linalg_trmm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matmul: alpha * op(tri(A)) @ B (or B @ op(tri(A))
    with rightside) (la_op.cc:297)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _t(tri) if transpose else tri
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register("_linalg_trsm")
def linalg_trsm(A, B, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular solve: X with op(tri(A)) @ X = alpha*B (or
    X @ op(tri(A)) = alpha*B with rightside) (la_op.cc:360)."""
    return lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)


@register("_linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    """sum(log(diag(A))) over the last two axes (la_op.cc:423)."""
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("_linalg_syrk")
def linalg_syrk(A, *, transpose=False, alpha=1.0):
    """alpha * A @ A^T (or A^T @ A with transpose) (la_op.cc:466)."""
    return alpha * (jnp.matmul(_t(A), A) if transpose
                    else jnp.matmul(A, _t(A)))


@register("_linalg_gelqf", num_outputs=2)
def linalg_gelqf(A):
    """LQ factorization A = L Q with Q orthonormal rows (la_op.cc:523);
    computed as the transposed QR of A^T."""
    q, r = jnp.linalg.qr(_t(A))
    return _t(r), _t(q)


@register("_linalg_syevd", num_outputs=2)
def linalg_syevd(A):
    """Eigendecomposition of symmetric A: returns (U, L) with
    A = U^T diag(L) U, U's ROWS the eigenvectors (la_op.cc:594)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@register("_linalg_extractdiag")
def linalg_extractdiag(A, *, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag")
def linalg_makediag(A, *, offset=0):
    """Batched diag(A, offset): A[..., i] lands at (i, i+offset) for
    offset >= 0, (i-offset, i) otherwise (numpy.diag semantics)."""
    m = A.shape[-1]
    n = m + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    i = jnp.arange(m)
    r = i if offset >= 0 else i - offset
    c = i + offset if offset >= 0 else i
    return out.at[..., r, c].set(A)


# single source of truth for the family — the nd/sym namespace shims
# build from this list
LINALG_NAMES = ("gemm", "gemm2", "potrf", "potri", "trmm", "trsm",
                "sumlogdiag", "syrk", "gelqf", "syevd", "extractdiag",
                "makediag")

for name in LINALG_NAMES:
    alias("_linalg_" + name, "linalg_" + name)
