"""Reductions and broadcast-to ops.

Parity: src/operator/tensor/broadcast_reduce-inl.h + broadcast_reduce_op.
XLA handles reduction tiling on the MXU/VPU; these are thin jnp wrappers.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, alias


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list,)):
        return tuple(axis)
    return axis


def _reduce(name, f):
    def op(data, *, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            if isinstance(ax, int):
                ax = (ax,)
            ax = tuple(i for i in range(data.ndim)
                       if i not in tuple(a % data.ndim for a in ax))
        return f(data, axis=ax, keepdims=keepdims)
    op.__name__ = name
    register(name)(op)


_reduce("sum", jnp.sum)
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("max", jnp.max)
_reduce("min", jnp.min)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
alias("sum", "sum_axis")
alias("max", "max_axis")
alias("min", "min_axis")


@register("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("argmax")
def argmax(data, *, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin")
def argmin(data, *, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register("broadcast_to")
def broadcast_to(data, *, shape):
    # MXNet semantics: 0 in target shape means "keep this dim"
    tgt = tuple(s if s != 0 else data.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_axis")
def broadcast_axis(data, *, axis, size):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


alias("broadcast_axis", "broadcast_axes")


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("cumsum")
def cumsum(data, *, axis=None, dtype=None):
    return jnp.cumsum(data, axis=axis, dtype=dtype)


@register("square_sum")
def square_sum(data, *, axis=None, keepdims=False):
    return jnp.sum(jnp.square(data), axis=_norm_axis(axis), keepdims=keepdims)


def _f32_out_dtypes(in_dtypes, params):
    """Index-returning ops always emit float32 (reference argmax/argmin
    return real_t indices), independent of the input dtype."""
    import numpy as _np2
    return list(in_dtypes), [_np2.dtype("float32")]


from .registry import set_op_meta as _set_op_meta  # noqa: E402
for _name in ("argmax", "argmin", "argmax_channel"):
    _set_op_meta(_name, dtype_hook=_f32_out_dtypes)
