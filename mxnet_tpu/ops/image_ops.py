"""Image operators (parity: src/operator/image/image_random-inl.h —
to_tensor, normalize, flips, color jitter, lighting; plus resize/crop used
by gluon transforms).

Layout convention matches the reference: images are HWC (or NHWC batched)
uint8/float; ``to_tensor`` converts to CHW float32 scaled to [0,1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register
from .. import random as _random


@register("_image_to_tensor")
def to_tensor(data):
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize")
def normalize(data, *, mean=0.0, std=1.0):
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    if data.ndim == 3:  # CHW
        shape = (-1, 1, 1)
    else:               # NCHW
        shape = (1, -1, 1, 1)
    return (data - jnp.reshape(mean, shape)) / jnp.reshape(std, shape)


def _flip(data, axis3):
    # axis3: axis in the HWC case; batched adds one
    return jnp.flip(data, axis=axis3 if data.ndim == 3 else axis3 + 1)


@register("_image_flip_left_right")
def flip_left_right(data):
    return _flip(data, 1)


@register("_image_flip_top_bottom")
def flip_top_bottom(data):
    return _flip(data, 0)


def _bernoulli():
    key = _random.next_key()
    return jax.random.bernoulli(key, 0.5)


@register("_image_random_flip_left_right", is_random=True)
def random_flip_left_right(data):
    return jnp.where(_bernoulli(), _flip(data, 1), data)


@register("_image_random_flip_top_bottom", is_random=True)
def random_flip_top_bottom(data):
    return jnp.where(_bernoulli(), _flip(data, 0), data)


def _uniform(lo, hi):
    key = _random.next_key()
    return jax.random.uniform(key, (), jnp.float32, lo, hi)


def _blend(a, b, alpha):
    out = alpha * a + (1.0 - alpha) * b
    return out


@register("_image_random_brightness", is_random=True)
def random_brightness(data, *, min_factor, max_factor):
    alpha = _uniform(min_factor, max_factor)
    return data.astype(jnp.float32) * alpha


# Plain numpy: a module-level jnp.array would force JAX backend
# initialization at import time (device work before the caller can pick a
# platform). jnp broadcasting accepts the np constant directly.
_GRAY = _np.array([0.299, 0.587, 0.114], _np.float32)


def _to_gray(x):
    # x: ...HWC
    return jnp.sum(x * _GRAY, axis=-1, keepdims=True)


@register("_image_random_contrast", is_random=True)
def random_contrast(data, *, min_factor, max_factor):
    alpha = _uniform(min_factor, max_factor)
    x = data.astype(jnp.float32)
    gray_mean = jnp.mean(_to_gray(x), axis=(-3, -2), keepdims=True)
    return _blend(x, gray_mean, alpha)


@register("_image_random_saturation", is_random=True)
def random_saturation(data, *, min_factor, max_factor):
    alpha = _uniform(min_factor, max_factor)
    x = data.astype(jnp.float32)
    return _blend(x, _to_gray(x), alpha)


@register("_image_random_hue", is_random=True)
def random_hue(data, *, min_factor, max_factor):
    """Hue rotation via the YIQ linear approximation the reference uses
    (image_random-inl.h RandomHue)."""
    alpha = _uniform(min_factor, max_factor)
    theta = (alpha - 1.0) * jnp.pi  # factor 1.0 -> no change
    u, w = jnp.cos(theta), jnp.sin(theta)
    # 4-decimal YIQ coefficients: the I and Q rows must sum to exactly
    # zero or gray pixels (R=G=B) pick up a hue-dependent cast (the
    # 3-decimal rounding leaves ±0.001 row residuals that t_rgb's ±1.7
    # entries amplify to ~3e-3 per channel)
    t_yiq = jnp.array([[0.299, 0.587, 0.114],
                       [0.5959, -0.2746, -0.3213],
                       [0.2115, -0.5227, 0.3112]], jnp.float32)
    t_rgb = jnp.array([[1.0, 0.9563, 0.6210],
                       [1.0, -0.2721, -0.6474],
                       [1.0, -1.1070, 1.7046]], jnp.float32)
    rot = jnp.array([[1.0, 0.0, 0.0],
                     [0.0, 0.0, 0.0],
                     [0.0, 0.0, 0.0]], jnp.float32) + \
        u * jnp.array([[0., 0., 0.], [0., 1., 0.], [0., 0., 1.]],
                      jnp.float32) + \
        w * jnp.array([[0., 0., 0.], [0., 0., 1.], [0., -1., 0.]],
                      jnp.float32)
    m = t_rgb @ rot @ t_yiq
    x = data.astype(jnp.float32)
    return jnp.einsum("...c,dc->...d", x, m)


@register("_image_random_color_jitter", is_random=True)
def random_color_jitter(data, *, brightness=0.0, contrast=0.0,
                        saturation=0.0, hue=0.0):
    x = data.astype(jnp.float32)
    if brightness > 0:
        x = x * _uniform(max(0.0, 1 - brightness), 1 + brightness)
    if contrast > 0:
        a = _uniform(max(0.0, 1 - contrast), 1 + contrast)
        x = _blend(x, jnp.mean(_to_gray(x), axis=(-3, -2), keepdims=True), a)
    if saturation > 0:
        a = _uniform(max(0.0, 1 - saturation), 1 + saturation)
        x = _blend(x, _to_gray(x), a)
    if hue > 0:
        x = random_hue(x, min_factor=1 - hue, max_factor=1 + hue)
    return x


@register("_image_random_lighting", is_random=True)
def random_lighting(data, *, alpha_std=0.05):
    """AlexNet-style PCA lighting noise (reference RandomLighting)."""
    key = _random.next_key()
    alpha = jax.random.normal(key, (3,), jnp.float32) * alpha_std
    eig_val = jnp.array([55.46, 4.794, 1.148], jnp.float32)
    eig_vec = jnp.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], jnp.float32)
    rgb = eig_vec @ (alpha * eig_val)
    return data.astype(jnp.float32) + rgb


@register("_image_resize")
def image_resize(data, *, size, keep_ratio=False, interp=1):
    """Resize HWC/NHWC to `size` (w, h) or square int; bilinear by default."""
    if isinstance(size, int):
        ow = oh = size
    else:
        ow, oh = size
    batched = data.ndim == 4
    x = data if batched else data[None]
    n, h, w, c = x.shape
    if keep_ratio and not isinstance(size, int):
        pass  # full ratio-preserving handled at the transform level
    # OpenCV interp codes -> jax.image methods; area (3) has no jax
    # equivalent and degrades to linear (antialiased) — closest for shrink
    method = {0: "nearest", 1: "linear", 2: "cubic", 3: "linear",
              4: "lanczos3"}.get(int(interp), "linear")
    out = jax.image.resize(x.astype(jnp.float32), (n, oh, ow, c), method)
    if data.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    else:
        out = out.astype(data.dtype)
    return out if batched else out[0]


@register("_image_crop")
def image_crop(data, *, x, y, width, height):
    if data.ndim == 3:
        return jax.lax.dynamic_slice(
            data, (y, x, 0), (height, width, data.shape[2]))
    return jax.lax.dynamic_slice(
        data, (0, y, x, 0), (data.shape[0], height, width, data.shape[3]))
