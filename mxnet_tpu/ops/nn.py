"""Neural-network operators.

Parity: src/operator/nn/ in the reference (Convolution, FullyConnected,
BatchNorm, Pooling, Activation, Dropout, softmax family, LayerNorm, Embedding
— fully_connected.cc:239-326 is the canonical registration). TPU-native
design notes:

* FullyConnected / Convolution / Deconvolution map straight to
  ``lax.dot_general`` / ``lax.conv_general_dilated`` → MXU. Layout semantics
  stay NCHW (reference default) while XLA's layout assignment is free to pick
  the TPU-optimal physical layout.
* Where the reference dispatches to MIOpen/cuDNN autotuned kernels
  (src/operator/nn/cudnn/), we rely on XLA conv emitters; no algo search.
* BatchNorm keeps running stats as explicit aux arrays (reference aux_states
  moving_mean/moving_var), returned as extra outputs so the functional core
  stays pure; the Gluon/Module layers wire them back to aux storage.
* Dropout draws from :mod:`mxnet_tpu.random` (trace-safe key threading).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias
from .. import random as _random


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------

@register("FullyConnected")
def fully_connected(data, weight, bias=None, *, num_hidden=0, no_bias=False,
                    flatten=True):
    if flatten:
        x = jnp.reshape(data, (data.shape[0], -1))
    else:
        x = data
    # weight layout: (num_hidden, in_units) — reference convention
    out = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


alias("FullyConnected", "fully_connected")


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

def _conv_dims(kernel):
    return len(kernel)


def _tuplize(v, n):
    if v is None:
        return (0,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution")
def convolution(data, weight, bias=None, *, kernel, num_filter,
                stride=None, dilate=None, pad=None, num_group=1,
                no_bias=False, layout=None):
    n = _conv_dims(kernel)
    stride = _tuplize(stride, n) or (1,) * n
    stride = tuple(s if s else 1 for s in stride)
    dilate = tuple(d if d else 1 for d in _tuplize(dilate, n))
    padding = [(p, p) for p in _tuplize(pad, n)]
    if n == 1:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCH", "OIH", "NCH"))
    elif n == 2:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    else:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                        ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * n)
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, *, kernel, num_filter,
                  stride=None, dilate=None, pad=None, adj=None,
                  target_shape=None, num_group=1, no_bias=True, layout=None):
    n = _conv_dims(kernel)
    stride = tuple(s if s else 1 for s in _tuplize(stride, n))
    dilate = tuple(d if d else 1 for d in _tuplize(dilate, n))
    pad_ = _tuplize(pad, n)
    adj_ = _tuplize(adj, n)
    # Transposed convolution == gradient of convolution wrt its input.
    # conv_general_dilated computes CORRELATION, so the kernel must be
    # spatially flipped to realize the transpose (caught by torch
    # conv_transpose2d parity); weight layout (reference):
    # (in_channels, num_filter//num_group, *kernel)
    weight = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    spatial = data.shape[2:]
    out_spatial = tuple(
        (spatial[i] - 1) * stride[i] - 2 * pad_[i]
        + dilate[i] * (kernel[i] - 1) + 1 + adj_[i]
        for i in range(n))
    if target_shape and any(int(t) > 0 for t in target_shape):
        # all-zero target_shape means UNSET (reference bCal guard)
        # reference DeconvolutionParam::InferPad (deconvolution-inl.h:121):
        # target_shape REPLACES user pad/adj — total = stride*(in-1) +
        # dilated_ksize - target, adj = total % 2, pad = (total+1)//2
        target = tuple(int(t) for t in target_shape)
        dksize = tuple(dilate[i] * (kernel[i] - 1) + 1 for i in range(n))
        total = tuple(stride[i] * (spatial[i] - 1) + dksize[i] - target[i]
                      for i in range(n))
        if any(t < 0 for t in total):
            raise ValueError("too big target shape %s (natural zero-pad "
                             "output is %s)" % (target, tuple(
                                 stride[i] * (spatial[i] - 1) + dksize[i]
                                 for i in range(n))))
        adj_ = tuple(t % 2 for t in total)
        pad_ = tuple((t + 1) // 2 for t in total)
        out_spatial = target
    # lax.conv_transpose with flipped kernel reproduces gradient-of-conv.
    if n == 2:
        dn = lax.conv_dimension_numbers(
            (data.shape[0], data.shape[1]) + out_spatial,
            weight.shape, ("NCHW", "IOHW", "NCHW"))
    elif n == 1:
        dn = lax.conv_dimension_numbers(
            (data.shape[0], data.shape[1]) + out_spatial,
            weight.shape, ("NCH", "IOH", "NCH"))
    else:
        dn = lax.conv_dimension_numbers(
            (data.shape[0], data.shape[1]) + out_spatial,
            weight.shape, ("NCDHW", "IODHW", "NCDHW"))
    pads = []
    for i in range(n):
        lo = dilate[i] * (kernel[i] - 1) - pad_[i]
        hi = dilate[i] * (kernel[i] - 1) - pad_[i] + adj_[i]
        pads.append((lo, hi))
    if num_group != 1:
        # grouped deconv: split channels, run per group, concat
        xs = jnp.split(data, num_group, axis=1)
        ws = jnp.split(weight, num_group, axis=0)
        outs = [lax.conv_general_dilated(
            x, w, window_strides=(1,) * n, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn)
            for x, w in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1)
    else:
        out = lax.conv_general_dilated(
            data, weight, window_strides=(1,) * n, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn)
    if not no_bias and bias is not None:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * n)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

@register("Pooling")
def pooling(data, *, kernel=(), pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, cudnn_off=False, p_value=2):
    n = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * n
        pad = (0,) * n
    stride = tuple(s if s else 1 for s in _tuplize(stride, n)) if not global_pool else (1,) * n
    pad_ = _tuplize(pad, n) if not global_pool else (0,) * n
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad_)
    if pooling_convention == "full":
        # ceil-mode output: add extra padding on the high side when needed
        extra = []
        for i in range(n):
            size = data.shape[2 + i] + 2 * pad_[i] - kernel[i]
            rem = size % stride[i]
            extra.append(0 if rem == 0 else stride[i] - rem)
        padding = ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(pad_, extra))
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        # reference pooling-inl.h: Lp pooling with integer p (1/2/3 common)
        p = int(p_value)
        if p == 1:
            return lax.reduce_window(jnp.abs(data), 0.0, lax.add, window,
                                     strides, padding)
        if p == 2:
            p2 = lax.reduce_window(jnp.square(data), 0.0, lax.add, window,
                                   strides, padding)
            return jnp.sqrt(p2)
        pp = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window,
                               strides, padding)
        return pp ** (1.0 / p)
    raise ValueError("unknown pool_type %r" % pool_type)


alias("Pooling", "pooling")


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

def _bn_widened_sums(x, red):
    """Per-channel sum and sum-of-squares of a low-precision tensor,
    accumulated in f32 *inside* the reduction via dot_general's
    preferred_element_type — no convert of the activation tensor.

    bf16·bf16 products are exact in f32 (8-bit mantissas), so the results
    equal an f32 upcast-then-reduce bit-for-bit up to summation order.
    """
    axis = [i for i in range(x.ndim) if i not in red][0]
    ones = jnp.ones(tuple(x.shape[i] for i in red), x.dtype)
    s1 = lax.dot_general(x, ones,
                         ((red, tuple(range(len(red)))), ((), ())),
                         preferred_element_type=jnp.float32)
    s2 = lax.dot_general(x, x, ((red, red), ((axis,), (axis,))),
                         preferred_element_type=jnp.float32)
    n = 1
    for i in red:
        n *= x.shape[i]
    return s1, s2, n


def _bn_coef_apply(x, axis, *cols32):
    """Concatenate per-channel f32 coefficient vectors, downcast with a
    single convert, and return them reshaped for broadcasting against x.
    One convert per BN per pass instead of one per full activation
    tensor."""
    C = x.shape[axis]
    coef = jnp.concatenate(cols32).astype(x.dtype)
    shape = [1] * x.ndim
    shape[axis] = C
    return [jnp.reshape(coef[i * C:(i + 1) * C], shape)
            for i in range(len(cols32))]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_lowp_train(x, g32, b32, eps, axis):
    out, mean, var, _ = _bn_lowp_fwd_impl(x, g32, b32, eps, axis)
    return out, mean, var


def _bn_lowp_fwd_impl(x, g32, b32, eps, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    s1, s2, n = _bn_widened_sums(x, red)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    scale = inv * g32
    shift = b32 - mean * scale
    sc, sh = _bn_coef_apply(x, axis, scale, shift)
    return x * sc + sh, mean, var, inv


def _bn_lowp_train_fwd(x, g32, b32, eps, axis):
    out, mean, var, inv = _bn_lowp_fwd_impl(x, g32, b32, eps, axis)
    return (out, mean, var), (x, g32, mean, inv)


def _bn_lowp_train_bwd(eps, axis, res, cots):
    dy, _dmean, _dvar = cots  # stat outputs carry no gradient
    x, g32, mean, inv = res
    red = tuple(i for i in range(x.ndim) if i != axis)
    ones = jnp.ones(tuple(x.shape[i] for i in red), x.dtype)
    s_dy = lax.dot_general(dy, ones,
                           ((red, tuple(range(len(red)))), ((), ())),
                           preferred_element_type=jnp.float32)
    s_dyx = lax.dot_general(dy, x, ((red, red), ((axis,), (axis,))),
                            preferred_element_type=jnp.float32)
    n = 1
    for i in red:
        n *= x.shape[i]
    dgamma = inv * (s_dyx - mean * s_dy)
    dbeta = s_dy
    # dx = A*dy + B*x + C with per-channel f32 coefficients, applied bf16
    A = g32 * inv
    B = -A * inv * dgamma / n
    Cc = -A * s_dy / n - B * mean
    a, b, c = _bn_coef_apply(x, axis, A, B, Cc)
    dx = dy * a + x * b + c
    return dx, dgamma, dbeta


_bn_lowp_train.defvjp(_bn_lowp_train_fwd, _bn_lowp_train_bwd)


@register("BatchNorm", num_outputs=5)
def batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _training=True):
    """Returns (out, batch_mean, batch_var, new_moving_mean, new_moving_var).

    Visible outputs follow the reference's FNumVisibleOutputs (3 when
    output_mean_var else 1); the trailing two are the updated aux states —
    the reference mutates moving stats in place (src/operator/nn/batch_norm.cc),
    our pure-functional form returns them and the invoke layer/executor
    commits them. Same observable semantics, XLA-friendly.

    Mixed precision: stats/scale math stays f32 regardless of data dtype
    (reference cuDNN BN semantics), but for bf16/f16 activations the f32
    widening happens *inside* the reductions (dot_general with
    preferred_element_type=f32) and the normalize/scale/shift runs in the
    data dtype off a single per-channel downcast — the activation tensor
    is never round-tripped through f32 in fwd or bwd.
    """
    axis = axis % data.ndim
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    g32 = g.astype(jnp.float32) if g.dtype != jnp.float32 else g
    b32 = beta.astype(jnp.float32) if beta.dtype != jnp.float32 else beta
    lowp = data.dtype in (jnp.bfloat16, jnp.float16)
    if _training and not use_global_stats:
        if lowp:
            out, mean, var = _bn_lowp_train(data, g32, b32, float(eps), axis)
        else:
            mean = jnp.mean(data, axis=red_axes)
            var = jnp.var(data, axis=red_axes)
        new_mean = moving_mean * momentum + mean * (1.0 - momentum)
        new_var = moving_var * momentum + var * (1.0 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    if not (_training and not use_global_stats and lowp):
        inv = lax.rsqrt(var + eps)
        if lowp:
            sc, sh = _bn_coef_apply(data, axis, inv * g32,
                                    b32 - mean * (inv * g32))
            out = data * sc + sh
        else:
            shape = [1] * data.ndim
            shape[axis] = data.shape[axis]
            out = (data - jnp.reshape(mean, shape)) \
                * jnp.reshape(inv * g32, shape) + jnp.reshape(b32, shape)
    return (out.astype(data.dtype), lax.stop_gradient(mean),
            lax.stop_gradient(var),
            lax.stop_gradient(new_mean), lax.stop_gradient(new_var))


@register("_rnn_begin_state")
def _rnn_begin_state(ref, *, state_shape, batch_axis=0):
    """Zero initial RNN state whose batch dim comes from `ref` (entries of
    0 in state_shape are replaced by ref.shape[batch_axis]); keeps
    shape inference flowing forward when cells unroll with default
    states."""
    shp = tuple(ref.shape[batch_axis] if int(s) == 0 else int(s)
                for s in state_shape)
    return jnp.zeros(shp, ref.dtype)


@register("LayerNorm")
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    return out * jnp.reshape(gamma, shape) + jnp.reshape(beta, shape)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, *, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return out * jnp.reshape(gamma, shape) + jnp.reshape(beta, shape)


@register("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
    else:  # spatial
        red = tuple(range(2, data.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / nrm


@register("LRN")
def lrn(data, *, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    window = jnp.stack([padded[:, i:i + data.shape[1]] for i in range(nsize)], 0).sum(0)
    return data / jnp.power(knorm + alpha * window / nsize, beta)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

@register("Activation")
def activation(data, *, act_type="relu"):
    acts = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
    }
    return acts[act_type](data)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, *, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        a, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, a * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "prelu":
        g = gamma
        shape = [1] * data.ndim
        if g.ndim == 1 and data.ndim > 1:
            shape[1] = g.shape[0]
            g = jnp.reshape(g, shape)
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        key = _random.next_key()
        slope_r = jax.random.uniform(key, data.shape, data.dtype,
                                     lower_bound, upper_bound)
        return jnp.where(data >= 0, data, slope_r * data)
    raise ValueError(act_type)


# ---------------------------------------------------------------------------
# Softmax family
# ---------------------------------------------------------------------------

@register("softmax")
def softmax(data, *, axis=-1, temperature=None, length=None):
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def softmin(data, *, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.softmax(-x, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    lp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    nll = -jnp.take_along_axis(lp, lab[:, None], axis=-1)
    return jnp.sum(nll)


def _softmax_output_impl(data, label, grad_scale, ignore_label, multi_output,
                         use_ignore, normalization, smooth_alpha):
    axis = 1 if multi_output else -1
    return jax.nn.softmax(data, axis=axis)


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _softmax_output_core(data, label, grad_scale, ignore_label, multi_output,
                         use_ignore, normalization, smooth_alpha):
    return _softmax_output_impl(data, label, grad_scale, ignore_label,
                                multi_output, use_ignore, normalization,
                                smooth_alpha)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, normalization, smooth_alpha):
    out = _softmax_output_impl(data, label, grad_scale, ignore_label,
                               multi_output, use_ignore, normalization,
                               smooth_alpha)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, multi_output, use_ignore,
                        normalization, smooth_alpha, res, g):
    """Loss-layer gradient: softmax(data) - one_hot(label), the reference's
    SoftmaxOutput backward (src/operator/softmax_output-inl.h) — the incoming
    cotangent is ignored (SoftmaxOutput is a head/loss op)."""
    out, label = res
    axis = 1 if multi_output else -1
    ncls = out.shape[axis]
    lab = label.astype(jnp.int32)
    oh = jax.nn.one_hot(lab, ncls, dtype=out.dtype)
    if smooth_alpha:
        oh = oh * (1.0 - smooth_alpha) + smooth_alpha / ncls
    if multi_output:
        # label shape (N, spatial...) -> one_hot gives (..., C); move C to axis 1
        oh = jnp.moveaxis(oh, -1, 1)
    grad = out - oh
    if use_ignore:
        mask = (lab != jnp.asarray(ignore_label, jnp.int32))
        if multi_output:
            grad = grad * mask[:, None].astype(grad.dtype)
        else:
            grad = grad * mask[..., None].astype(grad.dtype)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum((lab != jnp.asarray(ignore_label, jnp.int32))
                                    .astype(grad.dtype)), 1.0)
        scale = scale / valid
    return (grad * scale, jnp.zeros_like(label))


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput")
def softmax_output(data, label=None, *, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    if label is None:
        axis = 1 if multi_output else -1
        return jax.nn.softmax(data, axis=axis)
    return _softmax_output_core(data, label, grad_scale, ignore_label,
                                multi_output, use_ignore, normalization,
                                smooth_alpha)


@register("CTCLoss")
def ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    # data: (T, N, C) activations (pre-softmax), label: (N, L); optional
    # per-sample lengths (reference src/operator/nn/ctc_loss: 4-input op)
    logp = jax.nn.log_softmax(data, axis=-1)
    T, N, C = data.shape
    lab = label.astype(jnp.int32)
    L = lab.shape[1]
    blank = 0 if blank_label == "first" else C - 1
    # extended label sequence with blanks: length 2L+1
    ext = jnp.full((N, 2 * L + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    S = 2 * L + 1
    neg_inf = -1e30

    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        # count of non-(-1/0-pad) entries; MXNet pads with -1 or 0
        pad_mask = (lab >= 0) & (lab != 0) if blank == 0 else (lab >= 0)
        lab_len = jnp.sum(pad_mask.astype(jnp.int32), axis=1)
    ext_len = 2 * lab_len + 1

    def step(alpha_prev, logp_t):
        # alpha: (N, S)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (N, S)
        a0 = alpha_prev
        a1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha_prev[:, :-1]], 1)
        a2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha_prev[:, :-2]], 1)
        # skip allowed only when ext[s] != blank and ext[s] != ext[s-2]
        ext_m2 = jnp.concatenate([jnp.full((N, 2), -2, jnp.int32), ext[:, :-2]], 1)
        can_skip = (ext != blank) & (ext != ext_m2)
        a2 = jnp.where(can_skip, a2, neg_inf)
        alpha = jnp.logaddexp(jnp.logaddexp(a0, a1), a2) + emit
        return alpha, alpha

    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_lab = ext[:, 1]
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(logp[0], first_lab[:, None], 1)[:, 0])
    alpha_T, alpha_seq = lax.scan(step, alpha0, logp[1:])
    if use_data_lengths and data_lengths is not None:
        # per-sample final alpha at t = data_length-1
        alpha_all = jnp.concatenate([alpha0[None], alpha_seq], axis=0)  # (T,N,S)
        t_idx = jnp.clip(data_lengths.astype(jnp.int32) - 1, 0, T - 1)
        alpha_T = alpha_all[t_idx, jnp.arange(N)]                       # (N,S)
    idx_last = (ext_len - 1)[:, None]
    idx_prev = (ext_len - 2)[:, None]
    ll = jnp.logaddexp(
        jnp.take_along_axis(alpha_T, idx_last, 1),
        jnp.take_along_axis(alpha_T, jnp.maximum(idx_prev, 0), 1))[:, 0]
    return -ll


alias("CTCLoss", "ctc_loss")


# ---------------------------------------------------------------------------
# Dropout / Embedding
# ---------------------------------------------------------------------------

@register("Dropout", is_random=True)
def dropout(data, *, p=0.5, mode="training", axes=(), cudnn_off=False,
            _training=True):
    # mode='always': apply dropout regardless of train/predict (MC dropout;
    # reference src/operator/nn/dropout-inl.h DropoutParam::mode)
    if (not _training and mode != "always") or p <= 0.0:
        return data * 1.0
    key = _random.next_key()
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    return jnp.where(mask, data / keep, jnp.zeros_like(data))


def _maybe_take_rows(data, weight):
    """Kernel-tier dispatch for the embedding gather: the Pallas
    scalar-prefetch row-DMA kernel when the tier policy + guard allow,
    else None (caller falls back to jnp.take)."""
    from ..kernels import tier as _ktier
    if not _ktier.enabled():
        return None
    from ..kernels import take as _ktake
    reason = _ktake.eligible(weight.shape, weight.dtype, data.shape,
                             data.dtype)
    go, cfg = _ktier.should_dispatch(
        _ktake.OP_NAME,
        _ktake.shape_key_shapes(weight.shape, data.shape),
        weight.dtype, guard_reason=reason)
    if not go:
        return None
    return _ktake.take_rows(weight, data, config=cfg)


@register("Embedding")
def embedding(data, weight, *, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    out = _maybe_take_rows(data, weight)
    if out is not None:
        return out
    # clip mode: the reference take/Embedding clamp out-of-range rows,
    # and the Pallas take_rows kernel clips too — dispatch must never
    # change numerics
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")


@register("_contrib_SparseEmbedding")
def sparse_embedding(data, weight, *, input_dim=0, output_dim=0,
                     dtype="float32", deterministic=False):
    """Embedding whose weight gradient is row-sparse (parity:
    src/operator/tensor/indexing_op.cc:98-133 SparseEmbedding). The
    forward is a plain gather; the sparse-gradient contract lives in the
    storage layer (gluon Parameter grad_stype='row_sparse' /
    RowSparseNDArray), which the optimizers' lazy row updates consume —
    XLA scatters the VJP, so there is no dense-vs-rsp kernel split to
    reproduce."""
    out = _maybe_take_rows(data, weight)
    if out is not None:
        return out
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")


# ---------------------------------------------------------------------------
# RNN (fused; reference: src/operator/rnn-inl.h, cudnn_rnn-inl.h)
# ---------------------------------------------------------------------------

def _lstm_cell(xproj, h, c, wh, bh):
    # xproj = x @ wx.T + bx, hoisted out of the scan (see rnn())
    gates = xproj + h @ wh.T + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_cell(xproj, h, wh, bh):
    xr, xz, xn = jnp.split(xproj, 3, axis=-1)
    hr, hz, hn = jnp.split(h @ wh.T + bh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


def _rnn_cell(xproj, h, wh, bh, act):
    return act(xproj + h @ wh.T + bh)


def _rnn_param_shapes(mode, input_size, state_size, num_layers, bidirectional):
    mult = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    dirs = 2 if bidirectional else 1
    shapes = []
    for layer in range(num_layers):
        for d in range(dirs):
            in_sz = input_size if layer == 0 else state_size * dirs
            shapes.append(("wx", (mult * state_size, in_sz)))
            shapes.append(("wh", (mult * state_size, state_size)))
    for layer in range(num_layers):
        for d in range(dirs):
            shapes.append(("bx", (mult * state_size,)))
            shapes.append(("bh", (mult * state_size,)))
    return shapes


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    return sum(int(jnp.prod(jnp.asarray(s))) for _, s in
               _rnn_param_shapes(mode, input_size, state_size, num_layers, bidirectional))


def _unpack_rnn_params(params, mode, input_size, state_size, num_layers,
                       bidirectional):
    shapes = _rnn_param_shapes(mode, input_size, state_size, num_layers, bidirectional)
    out, off = [], 0
    for _, s in shapes:
        n = 1
        for d in s:
            n *= d
        out.append(jnp.reshape(lax.dynamic_slice(params, (off,), (n,)), s))
        off += n
    return out


@register("RNN", num_outputs=lambda p: 3 if p.get("mode") == "lstm" and p.get("state_outputs") else (2 if p.get("state_outputs") else 1))
def rnn(data, parameters, state, state_cell=None, *, state_size, num_layers,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        projection_size=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, lstm_state_clip_nan=False):
    """Fused multi-layer RNN over ``lax.scan`` (time major: (T, N, I)).

    The TPU analog of the reference's miopenRNN fused kernels
    (src/operator/cudnn_rnn-inl.h:43), with the cuDNN scheduling trick
    done at the XLA level: the input projection ``x @ wx.T + bx`` for ALL
    timesteps is hoisted out of the scan into one (T*N, I)x(I, G*H)
    matmul — a large, MXU-efficient contraction — so the sequential scan
    body carries only the (N, H)x(H, G*H) recurrence.
    """
    T, N, I = data.shape
    dirs = 2 if bidirectional else 1
    flat = _unpack_rnn_params(parameters, mode, I, state_size, num_layers,
                              bidirectional)
    mult = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    n_gate_pairs = num_layers * dirs
    wxs = flat[0:2 * n_gate_pairs:2]
    whs = flat[1:2 * n_gate_pairs:2]
    bxs = flat[2 * n_gate_pairs::2]
    bhs = flat[2 * n_gate_pairs + 1::2]

    h0 = state  # (L*dirs, N, H)
    c0 = state_cell if mode == "lstm" else None
    x = data
    h_finals, c_finals = [], []
    act = jnp.tanh if mode != "rnn_relu" else jax.nn.relu

    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            li = layer * dirs + d
            wx, wh, bx, bh = wxs[li], whs[li], bxs[li], bhs[li]
            xs = x if d == 0 else jnp.flip(x, axis=0)
            # whole-sequence input projection: one big MXU matmul
            xp = jnp.einsum("tni,gi->tng", xs, wx) + bx
            if mode == "lstm":
                def step(carry, xt):
                    h, c = carry
                    h2, c2 = _lstm_cell(xt, h, c, wh, bh)
                    return (h2, c2), h2
                (hT, cT), ys = lax.scan(step, (h0[li], c0[li]), xp)
                c_finals.append(cT)
            elif mode == "gru":
                def step(h, xt):
                    h2 = _gru_cell(xt, h, wh, bh)
                    return h2, h2
                hT, ys = lax.scan(step, h0[li], xp)
            else:
                def step(h, xt):
                    h2 = _rnn_cell(xt, h, wh, bh, act)
                    return h2, h2
                hT, ys = lax.scan(step, h0[li], xp)
            h_finals.append(hT)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs_dir.append(ys)
        x = jnp.concatenate(outs_dir, axis=-1) if dirs == 2 else outs_dir[0]
        if p > 0.0 and layer < num_layers - 1:
            key = _random.next_key()
            mask = jax.random.bernoulli(key, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), jnp.zeros_like(x))

    hF = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        cF = jnp.stack(c_finals, axis=0)
        if state_outputs:
            return x, hF, cF
        return x
    if state_outputs:
        return x, hF
    return x


# ---------------------------------------------------------------------------
# Upsampling / resize
# ---------------------------------------------------------------------------

@register("UpSampling")
def upsampling(*data, scale=2, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    x = data[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        if len(data) > 1 and multi_input_mode == "concat":
            outs = [out]
            for d in data[1:]:
                s = out.shape[2] // d.shape[2]
                outs.append(jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3))
            out = jnp.concatenate(outs, axis=1)
        return out
    raise NotImplementedError("bilinear UpSampling via Deconvolution")


def _interp_axis_align_corners(x, out_len, axis):
    """1-D linear interpolation along `axis` with the reference's
    align-corners ratio (bilinear_resize.cc:69: rwidth = (in-1)/(out-1);
    jax.image.resize uses half-pixel centers, which the reference kernel
    does NOT)."""
    in_len = x.shape[axis]
    if out_len == in_len:
        return x
    if out_len > 1 and in_len > 1:
        pos = jnp.arange(out_len, dtype=jnp.float32) \
            * ((in_len - 1) / (out_len - 1))
    else:
        pos = jnp.zeros((out_len,), jnp.float32)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_len - 1)
    t = pos - lo
    shape = [1] * x.ndim
    shape[axis] = out_len
    t = t.reshape(shape).astype(x.dtype)
    return jnp.take(x, lo, axis=axis) * (1 - t) \
        + jnp.take(x, hi, axis=axis) * t


@register("_contrib_BilinearResize2D")
def bilinear_resize(data, *, height=0, width=0, scale_height=None, scale_width=None):
    n, c, h, w = data.shape
    oh = height or int(h * scale_height)
    ow = width or int(w * scale_width)
    out = _interp_axis_align_corners(data, oh, 2)
    return _interp_axis_align_corners(out, ow, 3)


def _adaptive_pool_matrix(in_len, out_len, dtype):
    """Averaging matrix A (out,in): A[i,j] = 1/len(win_i) for j in the
    reference's variable window [floor(i*in/out), ceil((i+1)*in/out))
    (contrib/adaptive_avg_pooling.cc). Dense matmul form: exact for any
    size ratio and XLA/MXU-friendly."""
    import numpy as _np
    a = _np.zeros((out_len, in_len), _np.float32)
    for i in range(out_len):
        s = (i * in_len) // out_len
        e = -(-((i + 1) * in_len) // out_len)   # ceil
        a[i, s:e] = 1.0 / (e - s)
    return jnp.asarray(a, dtype)


@register("_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pool(data, *, output_size=1):
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    n, c, h, w = data.shape
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    ah = _adaptive_pool_matrix(h, oh, data.dtype)     # (oh, h)
    aw = _adaptive_pool_matrix(w, ow, data.dtype)     # (ow, w)
    return jnp.einsum("oh,nchw,pw->ncop", ah, data, aw)


# ---------------------------------------------------------------------------
# Symbolic-layer metadata: parameter-shape inference hooks + aux slots.
# Role parity: the backward direction of the reference's FInferShape
# (e.g. src/operator/nn/fully_connected.cc FullyConnectedShape infers the
# weight shape from data + num_hidden) and aux_states declaration
# (batch_norm.cc moving_mean/moving_var).
# ---------------------------------------------------------------------------
from .registry import set_op_meta as _set_op_meta


def _fc_shapes(ins, p):
    data, weight, bias = (ins + [None] * 3)[:3]
    nh = int(p.get("num_hidden", 0))
    out = list(ins)
    if data is not None:
        in_units = 1
        if p.get("flatten", True):
            for d in data[1:]:
                in_units *= d
        else:
            in_units = data[-1]
        if len(ins) > 1 and ins[1] is None:
            out[1] = (nh, in_units)
    if len(ins) > 2 and ins[2] is None:
        out[2] = (nh,)
    return out


def _conv_shapes(ins, p):
    data, weight, bias = (ins + [None] * 3)[:3]
    nf = int(p["num_filter"])
    k = tuple(p["kernel"])
    ng = int(p.get("num_group", 1))
    out = list(ins)
    if data is not None and len(ins) > 1 and ins[1] is None:
        out[1] = (nf, data[1] // ng) + k
    if len(ins) > 2 and ins[2] is None:
        out[2] = (nf,)
    return out


def _deconv_shapes(ins, p):
    data, weight, bias = (ins + [None] * 3)[:3]
    nf = int(p["num_filter"])
    k = tuple(p["kernel"])
    ng = int(p.get("num_group", 1))
    out = list(ins)
    if data is not None and len(ins) > 1 and ins[1] is None:
        out[1] = (data[1], nf // ng) + k
    if len(ins) > 2 and ins[2] is None:
        out[2] = (nf,)
    return out


def _bn_shapes(ins, p):
    data = ins[0]
    out = list(ins)
    if data is not None:
        ax = int(p.get("axis", 1)) % len(data)
        c = (data[ax],)
        for i in range(1, min(5, len(ins))):
            if out[i] is None:
                out[i] = c
    return out


def _ln_shapes(ins, p):
    data = ins[0]
    out = list(ins)
    if data is not None:
        ax = int(p.get("axis", -1)) % len(data)
        c = (data[ax],)
        for i in range(1, min(3, len(ins))):
            if out[i] is None:
                out[i] = c
    return out


def _in_shapes(ins, p):
    data = ins[0]
    out = list(ins)
    if data is not None:
        c = (data[1],)
        for i in range(1, min(3, len(ins))):
            if out[i] is None:
                out[i] = c
    return out


def _embedding_shapes(ins, p):
    out = list(ins)
    if len(ins) > 1 and ins[1] is None:
        out[1] = (int(p["input_dim"]), int(p["output_dim"]))
    return out


def _rnn_shapes(ins, p):
    data, params_, state = (ins + [None] * 4)[:3]
    out = list(ins)
    if data is not None:
        H = int(p["state_size"])
        L = int(p["num_layers"])
        dirs = 2 if p.get("bidirectional") else 1
        I = data[2]
        if len(ins) > 1 and out[1] is None:
            out[1] = (rnn_param_size(p.get("mode", "lstm"), I, H, L,
                                     bool(p.get("bidirectional", False))),)
        if len(ins) > 2 and out[2] is None:
            out[2] = (L * dirs, data[1], H)
        if len(ins) > 3 and out[3] is None:
            out[3] = (L * dirs, data[1], H)
    return out


def _prelu_shapes(ins, p):
    out = list(ins)
    if p.get("act_type") == "prelu" and len(ins) > 1 and ins[1] is None and ins[0] is not None:
        out[1] = (ins[0][1] if len(ins[0]) > 1 else 1,)
    return out


_set_op_meta("FullyConnected", shape_hook=_fc_shapes)
_set_op_meta("Convolution", shape_hook=_conv_shapes)
_set_op_meta("Deconvolution", shape_hook=_deconv_shapes)
def _bn_dtypes(in_dtypes, params):
    """fp16/bf16 data keeps f32 gamma/beta/moving stats and f32 batch
    stats (reference BN FInferType pins aux float32)."""
    import numpy as _np2
    d = in_dtypes[0] if in_dtypes and in_dtypes[0] is not None \
        else _np2.dtype("float32")
    f32 = _np2.dtype("float32")
    return [d, f32, f32, f32, f32], [d, f32, f32, f32, f32]


_set_op_meta("BatchNorm", shape_hook=_bn_shapes, dtype_hook=_bn_dtypes,
             aux_inputs=(3, 4), aux_outputs=(3, 4),
             num_visible_outputs=lambda p: 3 if p.get("output_mean_var") else 1)
_set_op_meta("LayerNorm", shape_hook=_ln_shapes)
_set_op_meta("InstanceNorm", shape_hook=_in_shapes)
_set_op_meta("Embedding", shape_hook=_embedding_shapes)
_set_op_meta("_contrib_SparseEmbedding", shape_hook=_embedding_shapes)
_set_op_meta("RNN", shape_hook=_rnn_shapes)
_set_op_meta("LeakyReLU", shape_hook=_prelu_shapes)


# ---------------------------------------------------------------------------
# Regression output heads (reference: src/operator/regression_output-inl.h)
# Forward is identity/sigmoid; backward seeds (pred - label)/batch like the
# reference, via custom_vjp (loss-head convention as SoftmaxOutput).
# ---------------------------------------------------------------------------

def _regression_core(transform, grad_fn):
    @_partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return transform(data)

    def fwd(data, label, grad_scale):
        out = transform(data)
        return out, (out, label)

    def bwd(grad_scale, res, g):
        out, label = res
        # reference scales by per-sample output count (label.Size()/batch),
        # NOT by batch size (src/operator/regression_output-inl.h backward)
        num_output = max(label.size // label.shape[0], 1)
        grad = grad_fn(out, label) * (grad_scale / num_output)
        return (grad, jnp.zeros_like(label))

    core.defvjp(fwd, bwd)
    return core


_linreg_core = _regression_core(lambda x: x * 1.0, lambda o, l: o - l.reshape(o.shape))
_maereg_core = _regression_core(lambda x: x * 1.0,
                                lambda o, l: jnp.sign(o - l.reshape(o.shape)))
_logreg_core = _regression_core(jax.nn.sigmoid,
                                lambda o, l: o - l.reshape(o.shape))


@register("LinearRegressionOutput")
def linear_regression_output(data, label=None, *, grad_scale=1.0):
    if label is None:
        return data * 1.0
    return _linreg_core(data, label, grad_scale)


@register("MAERegressionOutput")
def mae_regression_output(data, label=None, *, grad_scale=1.0):
    if label is None:
        return data * 1.0
    return _maereg_core(data, label, grad_scale)


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label=None, *, grad_scale=1.0):
    if label is None:
        return jax.nn.sigmoid(data)
    return _logreg_core(data, label, grad_scale)


@_partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _make_loss_core(data, grad_scale, normalization, valid_thresh):
    return data * 1.0


def _make_loss_fwd(data, grad_scale, normalization, valid_thresh):
    return data * 1.0, data


def _make_loss_bwd(grad_scale, normalization, valid_thresh, data, g):
    """MakeLoss backward (reference src/operator/make_loss-inl.h:92-118):
    the input IS the loss, so its gradient is the constant grad_scale —
    divided by batch ('batch') or by the count of elements above
    valid_thresh ('valid'). The incoming cotangent is ignored (head op
    seeded with all-ones, like SoftmaxOutput)."""
    scale = jnp.asarray(grad_scale, data.dtype)
    if normalization == "batch":
        scale = scale / data.shape[0]
    elif normalization == "valid":
        valid = jnp.maximum(
            jnp.sum((data > valid_thresh).astype(data.dtype)), 1.0)
        scale = scale / valid
    return (jnp.full(data.shape, scale, data.dtype),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss")
def make_loss(data, *, grad_scale=1.0, normalization="null",
              valid_thresh=0.0):
    """Turn any symbol into a loss head (reference make_loss.cc): forward
    is identity; backward injects grad_scale (grad_scale=0 makes a
    monitoring output that contributes no gradient, the SSD pattern)."""
    return _make_loss_core(data, float(grad_scale), normalization,
                           float(valid_thresh))


alias("MakeLoss", "make_loss")


def _softmax_out_shapes(ins, p):
    out = list(ins)
    data = ins[0]
    if data is not None and len(ins) > 1 and ins[1] is None:
        if p.get("multi_output"):
            out[1] = (data[0],) + tuple(data[2:])
        else:
            out[1] = tuple(data[:-1])
    return out


def _reg_out_shapes(ins, p):
    out = list(ins)
    if ins[0] is not None and len(ins) > 1 and ins[1] is None:
        out[1] = tuple(ins[0])
    return out


_set_op_meta("SoftmaxOutput", shape_hook=_softmax_out_shapes)
_set_op_meta("softmax_cross_entropy", shape_hook=_softmax_out_shapes)
_set_op_meta("LinearRegressionOutput", shape_hook=_reg_out_shapes)
_set_op_meta("MAERegressionOutput", shape_hook=_reg_out_shapes)
_set_op_meta("LogisticRegressionOutput", shape_hook=_reg_out_shapes)
